"""Drift detector — the flywheel's data-loop sensor (docs/FLYWHEEL.md).

The serving tier already records every request's size into a
:class:`~hydragnn_tpu.graphs.packing.SizeHistogram` (serve/metrics.py); the
ladder the batcher runs on was fitted to SOME observed distribution
(``fit_ladder``'s input — the "source"). This module closes the sensing
half of the data loop: a windowed total-variation distance between recent
traffic and the source distribution (``graphs/packing.histogram_distance``
— both sides quantized to compiled-shape bins, so only mass that MOVES
ACROSS a shape boundary registers), pushed through a hysteresis state
machine so boundary noise cannot flap the expensive actuator (ladder refit
+ fleet-wide swap) on and off.

Hysteresis contract:

* **enter**: the detector reports drift only after ``sustain`` CONSECUTIVE
  evaluations at distance >= ``high``;
* **exit**: once drifted, it stays drifted until an evaluation lands below
  ``low`` (a refit calls :meth:`rebase`, which re-anchors the source to the
  new ladder's input and resets the machine);
* the band between ``low`` and ``high`` changes nothing in either state —
  that dead zone is the no-flap guarantee the tier-1 hysteresis test pins.

Thread-safety: observations arrive from the flywheel control thread while
``report()`` is read by status surfaces — all mutable state is
``# guarded-by:``-annotated under one instrumented lock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..analysis import tsan
from ..graphs.packing import SizeHistogram, histogram_distance

Rows = List[Tuple[int, int, int]]


def _as_rows(hist: "SizeHistogram | Sequence[Tuple[int, int, int]]") -> Rows:
    if isinstance(hist, SizeHistogram):
        return [(n, e, w) for (n, e), w in sorted(hist.graphs.items())]
    return [(int(n), int(e), int(w)) for n, e, w in hist]


class Hysteresis:
    """The bare sustain-to-enter / low-watermark-exit state machine — the
    dead-band contract shared by the drift detector and the fleet autopilot
    (pilot/autopilot.py watermarks ride the SAME machine, so both actuators
    inherit the no-flap guarantee from one implementation).

    * inactive -> active only after ``sustain`` CONSECUTIVE ``step`` values
      at or above ``high`` (any value below ``high`` resets the count —
      including values inside the band);
    * active -> inactive only on a value strictly below ``low``;
    * the band ``[low, high)`` holds whichever state the machine is in.

    Unlike the drift detector's thresholds, ``high``/``low`` are NOT bounded
    above by 1 — autopilot pressure is demand over capacity and legitimately
    exceeds 1 during a flash crowd. Not itself thread-safe: every holder
    (DriftDetector, Autopilot) steps and reads it under its own lock, the
    same external-guard pattern as the router's ``_ReplicaEntry``.
    """

    __slots__ = ("high", "low", "sustain", "_over", "_active",
                 "enters_total", "exits_total")

    def __init__(self, high: float, low: float, sustain: int = 3):
        if not (0.0 <= float(low) < float(high)):
            raise ValueError(
                f"hysteresis watermarks must satisfy 0 <= low < high, got "
                f"low={low!r} high={high!r} (equal watermarks would remove "
                "the dead band — the no-flap guarantee)"
            )
        if int(sustain) < 1:
            raise ValueError(f"sustain must be >= 1, got {sustain}")
        self.high = float(high)
        self.low = float(low)
        self.sustain = int(sustain)
        self._over = 0  # guarded-by: external(the holder's lock)
        self._active = False  # guarded-by: external(the holder's lock)
        self.enters_total = 0  # guarded-by: external(the holder's lock)
        self.exits_total = 0  # guarded-by: external(the holder's lock)

    @property
    def active(self) -> bool:
        return self._active

    @property
    def over(self) -> int:
        """Consecutive at-or-over-``high`` count while inactive."""
        return self._over

    def step(self, value: float) -> Optional[str]:
        """One evaluation: returns ``"entered"``, ``"exited"``, or None."""
        v = float(value)
        if not self._active:
            if v >= self.high:
                self._over += 1
                if self._over >= self.sustain:
                    self._active = True
                    self.enters_total += 1
                    return "entered"
            else:
                # Below HIGH resets the sustain count — including the
                # hysteresis band: entry requires consecutive evidence.
                self._over = 0
        else:
            if v < self.low:
                self._active = False
                self._over = 0
                self.exits_total += 1
                return "exited"
            # low <= v: stays active (the band holds the state).
        return None

    def reset(self) -> None:
        """Back to inactive with a cleared sustain count (transition
        counters are cumulative and survive — they are evidence)."""
        self._over = 0
        self._active = False


class DriftDetector:
    """Windowed histogram-distance drift detector with hysteresis."""

    def __init__(
        self,
        source: "SizeHistogram | Sequence[Tuple[int, int, int]]",
        high: float = 0.35,
        low: float = 0.15,
        window: int = 4,
        sustain: int = 3,
        mode: str = "mult64",
        step: int = 64,
        min_nodes: int = 8,
    ):
        if not (0.0 < low < high < 1.0):
            raise ValueError(
                f"drift thresholds must satisfy 0 < low < high < 1, got "
                f"low={low!r} high={high!r} (equal thresholds would remove "
                "the hysteresis band — the no-flap guarantee)"
            )
        if window < 1 or sustain < 1:
            raise ValueError(
                f"window and sustain must be >= 1, got window={window} "
                f"sustain={sustain}"
            )
        self.high = float(high)
        self.low = float(low)
        self.window = int(window)
        self.sustain = int(sustain)
        self._quant = {"mode": mode, "step": step, "min_nodes": min_nodes}
        source_rows = _as_rows(source)
        if not source_rows:
            raise ValueError("drift detector needs a non-empty source histogram")
        self._lock = tsan.instrument_lock(
            threading.Lock(), "DriftDetector._lock"
        )
        # The fitted ladder's source observations (rebased on refit).
        self._source: Rows = source_rows  # guarded-by: self._lock
        # Sliding window of per-tick observation blocks (each block is the
        # delta the flywheel pulled from serve metrics since its last tick).
        self._window: Deque[Rows] = deque(maxlen=self.window)  # guarded-by: self._lock
        # The shared sustain/dead-band machine (Hysteresis) — the autopilot
        # steps the same class for its scale watermarks.
        self._machine = Hysteresis(self.high, self.low, self.sustain)  # guarded-by: self._lock
        self._distance: Optional[float] = None  # last evaluation  # guarded-by: self._lock
        self.evals_total = 0  # guarded-by: self._lock

    # -------------------------------------------------------------- feeding
    def observe(
        self, block: "SizeHistogram | Sequence[Tuple[int, int, int]]"
    ) -> int:
        """Append one observation block (a tick's worth of request sizes) to
        the sliding window; empty blocks are ignored (an idle tick carries
        no distribution evidence). Returns the block's total weight."""
        rows = [(n, e, w) for n, e, w in _as_rows(block) if w > 0]
        weight = sum(w for _n, _e, w in rows)
        if rows:
            with self._lock:
                self._window.append(rows)
        return weight

    # ----------------------------------------------------------- evaluation
    def evaluate(self) -> Dict[str, Any]:
        """One state-machine step: distance of the merged window vs the
        source, then the hysteresis transition. Returns {distance, drifted,
        over, transition} where transition is ``"entered"``, ``"exited"``,
        or None. With an empty window the state is unchanged (distance
        None): no evidence is not evidence of drift."""
        with self._lock:
            merged = [row for block in self._window for row in block]
            source = self._source
        if not merged:
            with self._lock:
                self.evals_total += 1
                return {
                    "distance": None,
                    "drifted": self._machine.active,
                    "over": self._machine.over,
                    "transition": None,
                }
        d = histogram_distance(source, merged, **self._quant)
        with self._lock:
            self.evals_total += 1
            self._distance = d
            transition = self._machine.step(d)
            out = {
                "distance": round(d, 6),
                "drifted": self._machine.active,
                "over": self._machine.over,
                "transition": transition,
            }
        return out

    # --------------------------------------------------------------- refit
    def window_histogram(self) -> SizeHistogram:
        """The merged window as a SizeHistogram — what a drift-triggered
        refit hands to ``fit_ladder`` (the NEW traffic is the new source)."""
        hist = SizeHistogram()
        with self._lock:
            blocks = list(self._window)
        for block in blocks:
            for n, e, w in block:
                hist.record_graph(n, e, w)
        return hist

    def rebase(
        self, source: "SizeHistogram | Sequence[Tuple[int, int, int]]"
    ) -> None:
        """Re-anchor after a refit: the new ladder's source observations
        replace the old, the window and the state machine reset — post-swap
        traffic is judged against what the batcher now runs on."""
        rows = _as_rows(source)
        if not rows:
            raise ValueError("cannot rebase onto an empty source histogram")
        with self._lock:
            self._source = rows
            self._window.clear()
            self._machine.reset()
            self._distance = None

    # -------------------------------------------------------------- status
    @property
    def drifted(self) -> bool:
        with self._lock:
            return self._machine.active

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "drifted": self._machine.active,
                "distance": self._distance,
                "over": self._machine.over,
                "high": self.high,
                "low": self.low,
                "window": self.window,
                "sustain": self.sustain,
                "window_blocks": len(self._window),
                "evals_total": self.evals_total,
                "enters_total": self._machine.enters_total,
                "exits_total": self._machine.exits_total,
            }
