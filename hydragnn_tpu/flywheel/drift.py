"""Drift detector — the flywheel's data-loop sensor (docs/FLYWHEEL.md).

The serving tier already records every request's size into a
:class:`~hydragnn_tpu.graphs.packing.SizeHistogram` (serve/metrics.py); the
ladder the batcher runs on was fitted to SOME observed distribution
(``fit_ladder``'s input — the "source"). This module closes the sensing
half of the data loop: a windowed total-variation distance between recent
traffic and the source distribution (``graphs/packing.histogram_distance``
— both sides quantized to compiled-shape bins, so only mass that MOVES
ACROSS a shape boundary registers), pushed through a hysteresis state
machine so boundary noise cannot flap the expensive actuator (ladder refit
+ fleet-wide swap) on and off.

Hysteresis contract:

* **enter**: the detector reports drift only after ``sustain`` CONSECUTIVE
  evaluations at distance >= ``high``;
* **exit**: once drifted, it stays drifted until an evaluation lands below
  ``low`` (a refit calls :meth:`rebase`, which re-anchors the source to the
  new ladder's input and resets the machine);
* the band between ``low`` and ``high`` changes nothing in either state —
  that dead zone is the no-flap guarantee the tier-1 hysteresis test pins.

Thread-safety: observations arrive from the flywheel control thread while
``report()`` is read by status surfaces — all mutable state is
``# guarded-by:``-annotated under one instrumented lock.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..analysis import tsan
from ..graphs.packing import SizeHistogram, histogram_distance

Rows = List[Tuple[int, int, int]]


def _as_rows(hist: "SizeHistogram | Sequence[Tuple[int, int, int]]") -> Rows:
    if isinstance(hist, SizeHistogram):
        return [(n, e, w) for (n, e), w in sorted(hist.graphs.items())]
    return [(int(n), int(e), int(w)) for n, e, w in hist]


class DriftDetector:
    """Windowed histogram-distance drift detector with hysteresis."""

    def __init__(
        self,
        source: "SizeHistogram | Sequence[Tuple[int, int, int]]",
        high: float = 0.35,
        low: float = 0.15,
        window: int = 4,
        sustain: int = 3,
        mode: str = "mult64",
        step: int = 64,
        min_nodes: int = 8,
    ):
        if not (0.0 < low < high < 1.0):
            raise ValueError(
                f"drift thresholds must satisfy 0 < low < high < 1, got "
                f"low={low!r} high={high!r} (equal thresholds would remove "
                "the hysteresis band — the no-flap guarantee)"
            )
        if window < 1 or sustain < 1:
            raise ValueError(
                f"window and sustain must be >= 1, got window={window} "
                f"sustain={sustain}"
            )
        self.high = float(high)
        self.low = float(low)
        self.window = int(window)
        self.sustain = int(sustain)
        self._quant = {"mode": mode, "step": step, "min_nodes": min_nodes}
        source_rows = _as_rows(source)
        if not source_rows:
            raise ValueError("drift detector needs a non-empty source histogram")
        self._lock = tsan.instrument_lock(
            threading.Lock(), "DriftDetector._lock"
        )
        # The fitted ladder's source observations (rebased on refit).
        self._source: Rows = source_rows  # guarded-by: self._lock
        # Sliding window of per-tick observation blocks (each block is the
        # delta the flywheel pulled from serve metrics since its last tick).
        self._window: Deque[Rows] = deque(maxlen=self.window)  # guarded-by: self._lock
        self._over = 0  # consecutive evaluations >= high  # guarded-by: self._lock
        self._drifted = False  # guarded-by: self._lock
        self._distance: Optional[float] = None  # last evaluation  # guarded-by: self._lock
        self.evals_total = 0  # guarded-by: self._lock
        self.enters_total = 0  # guarded-by: self._lock
        self.exits_total = 0  # guarded-by: self._lock

    # -------------------------------------------------------------- feeding
    def observe(
        self, block: "SizeHistogram | Sequence[Tuple[int, int, int]]"
    ) -> int:
        """Append one observation block (a tick's worth of request sizes) to
        the sliding window; empty blocks are ignored (an idle tick carries
        no distribution evidence). Returns the block's total weight."""
        rows = [(n, e, w) for n, e, w in _as_rows(block) if w > 0]
        weight = sum(w for _n, _e, w in rows)
        if rows:
            with self._lock:
                self._window.append(rows)
        return weight

    # ----------------------------------------------------------- evaluation
    def evaluate(self) -> Dict[str, Any]:
        """One state-machine step: distance of the merged window vs the
        source, then the hysteresis transition. Returns {distance, drifted,
        over, transition} where transition is ``"entered"``, ``"exited"``,
        or None. With an empty window the state is unchanged (distance
        None): no evidence is not evidence of drift."""
        with self._lock:
            merged = [row for block in self._window for row in block]
            source = self._source
        if not merged:
            with self._lock:
                self.evals_total += 1
                return {
                    "distance": None,
                    "drifted": self._drifted,
                    "over": self._over,
                    "transition": None,
                }
        d = histogram_distance(source, merged, **self._quant)
        transition = None
        with self._lock:
            self.evals_total += 1
            self._distance = d
            if not self._drifted:
                if d >= self.high:
                    self._over += 1
                    if self._over >= self.sustain:
                        self._drifted = True
                        self.enters_total += 1
                        transition = "entered"
                else:
                    # Below HIGH resets the sustain count — including the
                    # hysteresis band: entry requires consecutive evidence.
                    self._over = 0
            else:
                if d < self.low:
                    self._drifted = False
                    self._over = 0
                    self.exits_total += 1
                    transition = "exited"
                # low <= d: stays drifted (the band holds the state).
            out = {
                "distance": round(d, 6),
                "drifted": self._drifted,
                "over": self._over,
                "transition": transition,
            }
        return out

    # --------------------------------------------------------------- refit
    def window_histogram(self) -> SizeHistogram:
        """The merged window as a SizeHistogram — what a drift-triggered
        refit hands to ``fit_ladder`` (the NEW traffic is the new source)."""
        hist = SizeHistogram()
        with self._lock:
            blocks = list(self._window)
        for block in blocks:
            for n, e, w in block:
                hist.record_graph(n, e, w)
        return hist

    def rebase(
        self, source: "SizeHistogram | Sequence[Tuple[int, int, int]]"
    ) -> None:
        """Re-anchor after a refit: the new ladder's source observations
        replace the old, the window and the state machine reset — post-swap
        traffic is judged against what the batcher now runs on."""
        rows = _as_rows(source)
        if not rows:
            raise ValueError("cannot rebase onto an empty source histogram")
        with self._lock:
            self._source = rows
            self._window.clear()
            self._over = 0
            self._drifted = False
            self._distance = None

    # -------------------------------------------------------------- status
    @property
    def drifted(self) -> bool:
        with self._lock:
            return self._drifted

    def report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "drifted": self._drifted,
                "distance": self._distance,
                "over": self._over,
                "high": self.high,
                "low": self.low,
                "window": self.window,
                "sustain": self.sustain,
                "window_blocks": len(self._window),
                "evals_total": self.evals_total,
                "enters_total": self.enters_total,
                "exits_total": self.exits_total,
            }
