"""graftloop — the continuous-learning flywheel (docs/FLYWHEEL.md).

Supervisor-mode control loop closing the two feedback loops ROADMAP item 4
left human-cranked: checkpoints auto-stage as shadow-gated candidates
(green gate → auto-promotion, red gate → quarantine + ``flywheel_reject``
flight dump), and serve-traffic size histograms drive drift-triggered
bucket-ladder refits swapped hot across the fleet.
"""

from .drift import DriftDetector, Hysteresis
from .loop import Flywheel, FlywheelConfig

__all__ = ["DriftDetector", "Flywheel", "FlywheelConfig", "Hysteresis"]
