"""On-disk AOT executable store — keys, entry format, manifest, GC
(docs/COMPILE_CACHE.md).

Entry files reuse the checkpoint layer's v2 integrity container
(checkpoint/format.encode: magic + per-section sha256 digests) and its
fsync'd unique-tmp + atomic-rename install (checkpoint/io.write_checkpoint_blob)
— one durability/integrity implementation for every artifact the stack
persists. A store entry is::

    <cache_dir>/<key-digest>.hexe       # v2 container:
        header:   {"kind": "graftcache-exe/v1", "exe_format": ..., "key": {...}}
        sections: {"executable": <bytes>, "trees": <pickled treedefs>}
    <cache_dir>/manifest.json           # advisory index (ls/gc); lookups go
                                        # by key digest, so a lost manifest
                                        # update can never serve a wrong entry

``exe_format`` is ``"pjrt"`` (``jax.experimental.serialize_executable``
payload — deserialization fires NO XLA compile event, so the recompile
sentinel and the telemetry ``jax/compiles`` counters stay truthful) or
``"stablehlo"`` (the lowering text, persisted where the backend cannot
serialize executables; hydration then recompiles from StableHLO while JAX's
built-in ``compilation_cache_dir`` — enabled under ``<cache_dir>/xla/`` —
absorbs the XLA wall).

Corruption policy: a damaged entry (bad magic, torn container, digest
mismatch, undecodable trees) is LOUD — ``FaultCounters['exec_cache_corrupt']``
increments, a ``cache/corrupt_fallback`` event lands in the telemetry ring —
and the entry is quarantined (renamed ``*.corrupt``) so the caller falls back
to a fresh compile; it is never a crash and never poisons the engine.

Concurrency: the store is written from the serve dispatcher, the warmup
caller, and restart paths, possibly from several PROCESSES sharing one
directory (replicas). Entry installs are atomic renames with writer-owned
unique tmp names (two writers of the same key: last completed rename wins,
both files are valid). The manifest is read-modify-write under the in-process
lock and merged with the on-disk state at each update, so concurrent
processes lose at most a bookkeeping row, never an entry.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..analysis import tsan
from ..checkpoint import format as ckpt_format
from ..checkpoint.format import CheckpointCorruptError, param_fingerprint
from ..checkpoint.io import atomic_write_json, write_checkpoint_blob

ENTRY_KIND = "graftcache-exe/v1"
ENTRY_SUFFIX = ".hexe"
MANIFEST = "manifest.json"


class CacheEntryError(RuntimeError):
    """A store entry failed integrity verification or deserialization."""


def environment_fingerprint() -> Dict[str, str]:
    """The environment half of every key: jax/jaxlib versions plus a
    backend + device-topology string. Deterministic across processes on the
    same box/config — the property the cross-process warm-start rests on.
    Codegen-affecting environment (XLA_FLAGS, LIBTPU_INIT_ARGS, x64 mode)
    folds into the topology string: an executable compiled under different
    compiler flags must read as a MISS, exactly as JAX's own compilation
    cache keys compile options (the bit-exact-vs-fresh-compile contract)."""
    import jax
    import jaxlib

    devices = jax.devices()
    codegen = hashlib.sha256(
        "|".join(
            (
                os.environ.get("XLA_FLAGS", ""),
                os.environ.get("LIBTPU_INIT_ARGS", ""),
                f"x64={bool(jax.config.jax_enable_x64)}",
            )
        ).encode()
    ).hexdigest()[:12]
    topology = (
        f"{jax.default_backend()}|{len(devices)}x{devices[0].device_kind}"
        f"|procs={jax.process_count()}|codegen={codegen}"
    )
    return {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "backend": jax.default_backend(),
        "topology": topology,
    }


def tree_signature(tree: Any) -> str:
    """Structure digest of an arbitrary pytree (key paths, shapes, dtypes) —
    the checkpoint layer's param-tree fingerprint applied to any argument
    tree. Two programs traced from signature-identical args lower
    identically for a fixed config, which is what makes this a safe
    argument-side key component."""
    return param_fingerprint(tree)


@dataclass(frozen=True)
class CacheKey:
    """Full environment+program fingerprint of one compiled executable.

    Every field participates in the digest; a mismatch in ANY of them is a
    cache miss (tests/test_compile_cache.py locks each rejection class).

    ``config_fingerprint`` is the caller's model/run identity — built on the
    checkpoint layer's param-tree fingerprint (serve: params+batch_stats
    structure + the model's field repr; train: run_training's digest over
    the Training+Architecture config blocks). ``flags`` carries program-mode
    switches (``donate``, ``guard``); ``bucket`` is the padded arena shape
    ``(N_pad, E_pad, G_pad)`` (zeros when the program is not bucket-shaped);
    ``args_digest`` is the full argument-signature fingerprint
    (:func:`tree_signature`), which subsumes the bucket for correctness —
    the bucket stays a named field for observability (ls/manifest).

    ``mesh`` is the graftmesh axis-layout component
    (``parallel.distributed.mesh_descriptor``, e.g. ``"data:4xgraph:2"``):
    shard_map programs compiled for one mesh shape must never hydrate
    another's entries even when every array shape agrees (the environment
    topology pins the device COUNT; this pins the axis FACTORIZATION).
    Empty = single-device program — omitted from the canonical JSON so every
    pre-graftmesh store digest (and warm store) is preserved."""

    program: str
    jax_version: str
    jaxlib_version: str
    backend: str
    topology: str
    config_fingerprint: str
    flags: Tuple[str, ...] = ()
    bucket: Tuple[int, int, int] = (0, 0, 0)
    args_digest: str = ""
    mesh: str = ""

    @classmethod
    def for_environment(
        cls,
        program: str,
        config_fingerprint: str,
        flags: Tuple[str, ...] = (),
        bucket: Tuple[int, int, int] = (0, 0, 0),
        args_digest: str = "",
        env: Optional[Dict[str, str]] = None,
        mesh: str = "",
    ) -> "CacheKey":
        env = env if env is not None else environment_fingerprint()
        return cls(
            program=program,
            jax_version=env["jax_version"],
            jaxlib_version=env["jaxlib_version"],
            backend=env["backend"],
            topology=env["topology"],
            config_fingerprint=config_fingerprint,
            flags=tuple(sorted(flags)),
            bucket=(int(bucket[0]), int(bucket[1]), int(bucket[2])),
            args_digest=args_digest,
            mesh=str(mesh),
        )

    def to_json(self) -> Dict[str, Any]:
        doc = asdict(self)
        doc["flags"] = list(self.flags)
        doc["bucket"] = list(self.bucket)
        if not self.mesh:
            # Canonical-JSON stability: single-device keys keep their
            # pre-graftmesh digests, so existing stores stay warm.
            doc.pop("mesh")
        return doc

    @classmethod
    def from_json(cls, doc: Dict[str, Any]) -> "CacheKey":
        bucket = doc.get("bucket") or (0, 0, 0)
        return cls(
            program=doc["program"],
            jax_version=doc["jax_version"],
            jaxlib_version=doc["jaxlib_version"],
            backend=doc["backend"],
            topology=doc["topology"],
            config_fingerprint=doc["config_fingerprint"],
            flags=tuple(doc.get("flags") or ()),
            bucket=(int(bucket[0]), int(bucket[1]), int(bucket[2])),
            args_digest=doc.get("args_digest", ""),
            mesh=doc.get("mesh", ""),
        )

    def digest(self) -> str:
        """Canonical-JSON sha256 — the entry filename and the identity the
        round-trip test pins (same fields ⇒ same digest across processes)."""
        blob = json.dumps(self.to_json(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()


class ExecutableStore:
    """Directory-backed executable store with verified reads and atomic
    writes. Thread-safe; multi-process-safe at the entry level (atomic
    renames), advisory at the manifest level (see module docstring)."""

    def __init__(self, cache_dir: str, keep_max_entries: int = 0):
        self.cache_dir = cache_dir
        # keep_max_entries <= 0: unbounded (GC only via the CLI / explicit
        # gc()); > 0: put() prunes oldest-serial entries beyond the cap.
        self.keep_max_entries = int(keep_max_entries)
        self._lock = tsan.instrument_lock(
            threading.Lock(), "ExecutableStore._lock"
        )
        os.makedirs(cache_dir, exist_ok=True)

    # ------------------------------------------------------------ path layout
    def entry_path(self, key: CacheKey) -> str:
        return os.path.join(self.cache_dir, key.digest() + ENTRY_SUFFIX)

    def _manifest_path(self) -> str:
        return os.path.join(self.cache_dir, MANIFEST)

    # ----------------------------------------------------------------- write
    def put(
        self,
        key: CacheKey,
        sections: Dict[str, bytes],
        exe_format: str,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Install one entry: digest container + fsync + atomic rename, then
        the advisory manifest row. Returns the entry path."""
        header = {
            "kind": ENTRY_KIND,
            "exe_format": exe_format,
            "key": key.to_json(),
        }
        blob = ckpt_format.encode(dict(sections), header)
        path = self.entry_path(key)
        write_checkpoint_blob(path, blob)
        with self._lock:
            self._manifest_add(key, exe_format, len(blob), extra or {})
        return path

    def _manifest_add(
        self, key: CacheKey, exe_format: str, nbytes: int, extra: Dict[str, Any]
    ) -> None:
        # Merge-with-disk read-modify-write: a concurrent process's rows are
        # re-read here, so the manifest converges instead of ping-ponging.
        manifest = self._read_manifest()
        entries = [
            e for e in manifest.get("entries", []) if e.get("digest") != key.digest()
        ]
        serial = max((e.get("serial", 0) for e in entries), default=0) + 1
        entries.append(
            {
                "digest": key.digest(),
                "key": key.to_json(),
                "exe_format": exe_format,
                "bytes": int(nbytes),
                "created_utc": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
                "serial": serial,
            }
            | ({"extra": extra} if extra else {})
        )
        if self.keep_max_entries > 0 and len(entries) > self.keep_max_entries:
            entries.sort(key=lambda e: e.get("serial", 0))
            for drop in entries[: -self.keep_max_entries]:
                self._remove_file(drop.get("digest", ""))
            entries = entries[-self.keep_max_entries :]
        atomic_write_json(
            self._manifest_path(),
            {"kind": "graftcache-manifest/v1", "entries": entries},
        )

    def _read_manifest(self) -> Dict[str, Any]:
        try:
            with open(self._manifest_path()) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def _remove_file(self, digest: str) -> None:
        if not digest:
            return
        try:
            os.remove(os.path.join(self.cache_dir, digest + ENTRY_SUFFIX))
        except OSError:
            pass

    # ------------------------------------------------------------------ read
    def get(self, key: CacheKey) -> Optional[Tuple[Dict[str, bytes], str]]:
        """Verified read of one entry → (sections, exe_format), or None on a
        miss. A CORRUPT entry (torn container, digest mismatch, key-field
        disagreement) is quarantined loudly and reads as a miss — the caller
        compiles fresh; the store never crashes a serving path."""
        path = self.entry_path(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                blob = f.read()
            header, sections = ckpt_format.decode(blob, path)
            if header.get("kind") != ENTRY_KIND:
                raise CheckpointCorruptError(
                    path, f"not a graftcache entry (kind={header.get('kind')!r})"
                )
            stored_key = CacheKey.from_json(header.get("key") or {})
            if stored_key != key:
                # A digest collision is cryptographically out of reach; a
                # disagreement here means the file was tampered with or a
                # foreign file landed under this name — same fallback.
                raise CheckpointCorruptError(path, "stored key != lookup key")
            return dict(sections), str(header.get("exe_format", "pjrt"))
        except ckpt_format.CheckpointError as e:
            self._quarantine(path, key, str(e))
            return None

    def _quarantine(self, path: str, key: CacheKey, reason: str) -> None:
        """Loud corruption fallback: count it, ring-event it, move the file
        aside so the follow-up fresh compile can re-install cleanly."""
        from ..faults import FaultCounters
        from ..telemetry import graftel as telemetry

        FaultCounters.inc("exec_cache_corrupt")
        telemetry.event(
            "cache/corrupt_fallback",
            program=key.program,
            bucket=list(key.bucket),
            entry=os.path.basename(path),
            reason=reason[:300],
        )
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            try:
                os.remove(path)
            except OSError:
                pass

    # ------------------------------------------------------- CLI / maintenance
    def ls(self) -> List[Dict[str, Any]]:
        """Manifest rows merged with the directory truth: rows whose entry
        file vanished are dropped, on-disk entries the manifest missed (a
        lost concurrent update) are listed from their own headers."""
        with self._lock:
            manifest = self._read_manifest()
        rows = {
            e.get("digest"): dict(e)
            for e in manifest.get("entries", [])
            if os.path.exists(
                os.path.join(self.cache_dir, str(e.get("digest")) + ENTRY_SUFFIX)
            )
        }
        for fname in sorted(os.listdir(self.cache_dir)):
            if not fname.endswith(ENTRY_SUFFIX):
                continue
            digest = fname[: -len(ENTRY_SUFFIX)]
            if digest in rows:
                continue
            report = self.verify_entry(os.path.join(self.cache_dir, fname))
            if report.get("ok"):
                rows[digest] = {
                    "digest": digest,
                    "key": report["key"],
                    "exe_format": report["exe_format"],
                    "bytes": report["bytes"],
                    "created_utc": None,
                    "serial": 0,
                }
        return [rows[d] for d in sorted(rows)]

    @staticmethod
    def verify_entry(path: str) -> Dict[str, Any]:
        """Non-raising integrity report for one entry file (the ``verify``
        CLI — the checkpoint CLI's verify analog)."""
        report: Dict[str, Any] = {"file": path}
        try:
            with open(path, "rb") as f:
                blob = f.read()
            header, sections = ckpt_format.decode(blob, path)
            if header.get("kind") != ENTRY_KIND:
                raise CheckpointCorruptError(
                    path, f"not a graftcache entry (kind={header.get('kind')!r})"
                )
        except ckpt_format.CheckpointError as e:
            report.update(ok=False, error=str(e))
            return report
        report.update(
            ok=True,
            key=header.get("key"),
            exe_format=header.get("exe_format"),
            bytes=len(blob),
            sections=sorted(sections),
        )
        return report

    def verify(self) -> List[Dict[str, Any]]:
        return [
            self.verify_entry(os.path.join(self.cache_dir, f))
            for f in sorted(os.listdir(self.cache_dir))
            if f.endswith(ENTRY_SUFFIX)
        ]

    def gc(self, keep_last: int = 0, max_age_days: Optional[float] = None) -> List[str]:
        """Prune entries beyond ``keep_last`` (newest-serial kept) and/or
        older than ``max_age_days`` (file mtime). Returns removed digests.
        Also sweeps ``*.corrupt`` quarantine files and stale ``*.tmp``."""
        removed: List[str] = []
        with self._lock:
            manifest = self._read_manifest()
            entries = sorted(
                manifest.get("entries", []), key=lambda e: e.get("serial", 0)
            )
            keep = entries[-keep_last:] if keep_last > 0 else list(entries)
            drop = entries[:-keep_last] if keep_last > 0 else []
            now = time.time()
            if max_age_days is not None:
                still = []
                for e in keep:
                    p = os.path.join(
                        self.cache_dir, str(e.get("digest")) + ENTRY_SUFFIX
                    )
                    try:
                        old = (now - os.path.getmtime(p)) > max_age_days * 86400.0
                    except OSError:
                        old = True
                    (drop if old else still).append(e)
                keep = still
            for e in drop:
                self._remove_file(str(e.get("digest")))
                removed.append(str(e.get("digest")))
            for fname in os.listdir(self.cache_dir):
                p = os.path.join(self.cache_dir, fname)
                if fname.endswith(".tmp"):
                    # A .tmp may be a LIVE concurrent writer's in-flight
                    # install (multi-replica shared store) — only sweep ones
                    # old enough that no real write is still running (the
                    # checkpoint layer scopes its sweep to run startup for
                    # the same reason).
                    try:
                        stale = (now - os.path.getmtime(p)) > 3600.0
                    except OSError:
                        continue
                    if not stale:
                        continue
                elif not fname.endswith(".corrupt"):
                    continue
                try:
                    os.remove(p)
                    removed.append(fname)
                except OSError:
                    pass
            atomic_write_json(
                self._manifest_path(),
                {"kind": "graftcache-manifest/v1", "entries": keep},
            )
        return removed


# ------------------------------------------------- executable (de)serialization
def serialize_compiled(compiled: Any) -> Optional[Dict[str, bytes]]:
    """``jax.stages.Compiled`` → store sections, or None when the backend
    cannot serialize executables (the StableHLO fallback engages then).
    Treedefs ride along pickled — custom pytree nodes (GraphBatch,
    TrainState, optax states) unpickle against the SAME registered types, so
    hydration must happen after the defining modules imported (they have:
    the engine/trainer import them before any lookup)."""
    try:
        from jax.experimental import serialize_executable as se

        payload, in_tree, out_tree = se.serialize(compiled)
        return {
            "executable": payload,
            "trees": pickle.dumps((in_tree, out_tree)),
        }
    except Exception:  # noqa: BLE001 — backend capability probe, not an error
        return None


def deserialize_compiled(sections: Dict[str, bytes]) -> Any:
    """Store sections → loaded executable. Raises :class:`CacheEntryError`
    on any decode failure (the registry turns that into quarantine + fresh
    compile). Deserialization fires NO XLA compile monitoring event — the
    sentinel-truthfulness property tests/test_compile_cache.py pins."""
    from jax.experimental import serialize_executable as se

    try:
        # graftlint: disable=pickle-load-outside-compat(pytree defs inside a GSHD cache container whose digest was verified before this call — no untrusted bytes reach the unpickler)
        in_tree, out_tree = pickle.loads(sections["trees"])
        return se.deserialize_and_load(
            sections["executable"], in_tree, out_tree
        )
    except Exception as e:  # noqa: BLE001 — one failure class for callers
        raise CacheEntryError(
            f"executable deserialization failed ({type(e).__name__}: {e})"
        ) from e


def enable_xla_fallback_cache(cache_dir: str) -> None:
    """Point JAX's built-in persistent compilation cache at
    ``<cache_dir>/xla`` — the warm-compile path on backends where executable
    serialization is unavailable (entries then persist the lowering only).
    Idempotent; thresholds dropped to zero so small programs cache too."""
    import jax

    xla_dir = os.path.join(cache_dir, "xla")
    os.makedirs(xla_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", xla_dir)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # noqa: BLE001 — knob names drift across jax versions
        pass
