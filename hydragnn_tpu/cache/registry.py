"""ExecutableRegistry — the shared in-memory executable cache in front of the
on-disk :class:`~hydragnn_tpu.cache.store.ExecutableStore`
(docs/COMPILE_CACHE.md).

One registry instance replaces both the serve engine's ``_executables`` dict
and the trainer's per-program compiled-step dispatch: every consumer goes
through the SAME locked lookup → (compile outside the lock) → store path:

1. locked in-memory get — the steady-state hit, one lock acquisition;
2. on miss, OUTSIDE the lock (a 10–50 s lowering must never block a
   concurrent submit or /healthz read): disk hydrate when a store is bound
   (verified read + deserialize — fires NO XLA compile event, so
   ``no_recompile()`` and the ``jax/compiles`` telemetry stay truthful),
   else ``lower().compile()`` fresh, then serialize+install into the store;
3. locked publish into the in-memory map — a racing duplicate compile is a
   benign last-wins overwrite of an equivalent executable.

Outcomes are counted into the graftel registry under ``cache/*``
(``cache/hit``, ``cache/hydrate``, ``cache/miss``, ``cache/hydrate_s``,
``cache/store_s``, ``cache/compile_s``) so every consumer's cache behavior
is visible on one surface (/metrics, train_metrics.prom, flight dumps).
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Any, Callable, Dict, Hashable, Optional, Tuple

from ..analysis import tsan
from ..telemetry import graftel as telemetry
from .store import (
    CacheEntryError,
    CacheKey,
    ExecutableStore,
    deserialize_compiled,
    enable_xla_fallback_cache,
    serialize_compiled,
)

# lookup_or_compile outcomes.
OUTCOME_MEMORY = "memory"
OUTCOME_DISK = "disk"
OUTCOME_COMPILED = "compiled"


class ExecutableRegistry:
    """Locked in-memory executable map + optional persistent store.

    ``mem_key`` (any hashable — the serve engine uses the padded bucket
    tuple, the trainer a (program, shape-signature) pair) addresses the
    in-memory map; the full :class:`CacheKey` addresses the disk store and
    is only consulted on an in-memory miss, so hit paths never pay
    fingerprint arithmetic."""

    def __init__(
        self, store: Optional[ExecutableStore] = None, name: str = "registry"
    ):
        self.name = name
        self._store = store
        self._lock = tsan.instrument_lock(
            threading.Lock(), f"ExecutableRegistry._lock[{name}]"
        )
        # program-keyed executables: written by warmup callers (main), the
        # serve dispatch thread, and restart paths.
        self._mem: Dict[Hashable, Any] = {}  # guarded-by: self._lock
        # One-time diagnostics (serialization unavailable on this backend).
        self._serialize_unavailable = False  # guarded-by: self._lock, dirty-reads(monotonic bool; a stale False retries serialization once more, which is harmless)

    # ------------------------------------------------------------- inspection
    @property
    def store(self) -> Optional[ExecutableStore]:
        return self._store

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def get(self, mem_key: Hashable) -> Optional[Any]:
        with self._lock:
            return self._mem.get(mem_key)

    # ------------------------------------------------------------ the one path
    def lookup_or_compile(
        self,
        mem_key: Hashable,
        key: "Optional[CacheKey | Callable[[], Optional[CacheKey]]]",
        lower: Callable[[], Any],
    ) -> Tuple[Any, str, float]:
        """THE lookup path: returns ``(executable, outcome, seconds)`` where
        outcome is ``"memory"`` | ``"disk"`` | ``"compiled"`` and seconds is
        the hydrate or compile wall (0.0 for memory hits). ``lower`` returns
        a ``jax.stages.Lowered`` (called only on a full miss). ``key`` may be
        a zero-arg callable producing the :class:`CacheKey` — it is invoked
        only on an in-memory miss, so hot hit paths never pay fingerprint
        arithmetic."""
        with self._lock:
            exe = self._mem.get(mem_key)
        if exe is not None:
            telemetry.counter("cache/hit")
            return exe, OUTCOME_MEMORY, 0.0

        if callable(key):
            key = key()
        outcome = OUTCOME_COMPILED
        seconds = 0.0
        exe = None
        if self._store is not None and key is not None:
            t0 = time.perf_counter()
            exe = self._hydrate(key)
            if exe is not None:
                seconds = time.perf_counter() - t0
                outcome = OUTCOME_DISK
                telemetry.counter("cache/hydrate")
                telemetry.counter("cache/hydrate_s", seconds)
        if exe is None:
            t0 = time.perf_counter()
            lowered = lower()
            compiled = lowered.compile()
            seconds = time.perf_counter() - t0
            telemetry.counter("cache/miss")
            telemetry.counter("cache/compile_s", seconds)
            if self._store is not None and key is not None:
                self._persist(key, compiled, lowered)
            exe = compiled

        with self._lock:
            # Racing duplicate (two threads missed the same key): last wins;
            # both executables are equivalent programs, so either is correct.
            self._mem[mem_key] = exe
        return exe, outcome, seconds

    def put(self, mem_key: Hashable, exe: Any) -> None:
        """Direct in-memory install (tests, pre-hydrated executables)."""
        with self._lock:
            self._mem[mem_key] = exe

    # ------------------------------------------------------------- disk halves
    def _hydrate(self, key: CacheKey) -> Optional[Any]:
        """Verified store read + deserialize, or None (miss / corrupt entry /
        StableHLO-only entry). Never raises: every failure class here must
        degrade to a fresh compile."""
        assert self._store is not None
        got = self._store.get(key)
        if got is None:
            return None
        sections, exe_format = got
        if exe_format != "pjrt":
            # StableHLO-only entry: the XLA fallback cache (enabled when the
            # entry was written) absorbs the compile wall; the entry itself
            # exists for diagnostics and ls/verify. Treat as a miss here.
            return None
        try:
            return deserialize_compiled(sections)
        except CacheEntryError as e:
            # Verified bytes that still fail to load (jax minor drift inside
            # an identical version string, foreign-arch payload): quarantine
            # exactly like corruption — loud, then fresh compile.
            self._store._quarantine(self._store.entry_path(key), key, str(e))
            return None

    def _persist(self, key: CacheKey, compiled: Any, lowered: Any = None) -> None:
        """Serialize + install one freshly compiled executable; on backends
        without executable serialization, persist the StableHLO lowering and
        enable JAX's built-in compilation cache instead. Store failures are
        warnings — a full disk must not fail the train/serve path."""
        assert self._store is not None
        t0 = time.perf_counter()
        try:
            sections = serialize_compiled(compiled)
            if sections is not None:
                self._store.put(key, sections, exe_format="pjrt")
            else:
                with self._lock:
                    first = not self._serialize_unavailable
                    self._serialize_unavailable = True
                if first:
                    warnings.warn(
                        f"graftcache[{self.name}]: backend "
                        f"{key.backend!r} cannot serialize executables; "
                        "persisting StableHLO and enabling JAX's built-in "
                        "compilation_cache_dir fallback",
                        RuntimeWarning,
                        stacklevel=3,
                    )
                enable_xla_fallback_cache(self._store.cache_dir)
                hlo = _lowering_text(lowered if lowered is not None else compiled)
                if hlo is not None:
                    self._store.put(
                        key,
                        {"stablehlo": hlo.encode()},
                        exe_format="stablehlo",
                    )
        except OSError as e:
            warnings.warn(
                f"graftcache[{self.name}]: store write failed ({e}); "
                "continuing without persistence",
                RuntimeWarning,
                stacklevel=3,
            )
            return
        telemetry.counter("cache/store")
        telemetry.counter("cache/store_s", time.perf_counter() - t0)


def _lowering_text(stage: Any) -> Optional[str]:
    """Best-effort StableHLO/HLO text of a Lowered (preferred) or Compiled
    stage — the fallback entry's payload."""
    try:
        return stage.as_text()
    except Exception:  # noqa: BLE001 — diagnostics-only payload
        return None
