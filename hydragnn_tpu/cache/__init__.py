"""graftcache — persistent compiled-executable store shared across runs,
restarts, and replicas (docs/COMPILE_CACHE.md).

The padded-arena contract compiles one executable per bucket shape, which
makes compile wall the dominant cold-start cost: BENCH_r05_hw measured 51.8 s
of bucketed serve warmup and 9.9 s of train compile, and every faults-layer
supervisor restart and every new serve replica paid it again. This package
makes those executables a durable artifact:

* :class:`CacheKey` — the full environment+program fingerprint an entry is
  keyed by: (jax/jaxlib version, backend + device-topology string, a config
  fingerprint built on the checkpoint layer's param-tree fingerprint,
  donation/guard flags, the padded bucket shape, and an argument-signature
  digest). Any component mismatching is a MISS — a cache can never hand a
  stale program to a changed environment.
* :class:`ExecutableStore` — the on-disk half: one integrity-checked
  container per entry (the checkpoint layer's digest + fsync'd atomic-rename
  pattern), an advisory manifest, a keep-policy GC, and a LOUD corruption
  fallback — a damaged entry is quarantined and recompiled fresh, never a
  crash.
* :class:`ExecutableRegistry` — the in-memory half the serve engine and the
  trainer share: ONE locked lookup → (compile outside the lock) → store
  path, with graftel ``cache/*`` counters and truthful sentinel accounting
  (a deserialized executable fires no XLA compile event — verified).

CLI: ``python -m hydragnn_tpu.cache ls|verify|gc <cache_dir>`` (mirrors the
checkpoint CLI).
"""

from .store import (
    CacheEntryError,
    CacheKey,
    ExecutableStore,
    environment_fingerprint,
    tree_signature,
)
from .registry import ExecutableRegistry

__all__ = [
    "CacheEntryError",
    "CacheKey",
    "ExecutableRegistry",
    "ExecutableStore",
    "environment_fingerprint",
    "tree_signature",
]
