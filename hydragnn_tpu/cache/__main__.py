"""graftcache operations CLI (docs/COMPILE_CACHE.md) — the checkpoint CLI's
analog for the compiled-executable store::

    python -m hydragnn_tpu.cache ls     <cache_dir> [--json]
    python -m hydragnn_tpu.cache verify <cache_dir> [--json]
    python -m hydragnn_tpu.cache gc     <cache_dir> [--keep-last K]
                                        [--max-age-days D] [--json]

``ls`` lists entries (program, bucket, backend, format, size) from the
manifest merged with the directory truth; ``verify`` integrity-checks every
entry container (exit nonzero if any fails) — the preflight before trusting
a copied-around cache directory; ``gc`` applies the keep policy and sweeps
quarantine/tmp litter.
"""

from __future__ import annotations

import argparse
import json
import sys

from .store import ExecutableStore


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.cache",
        description="Inspect, verify, or garbage-collect a graftcache "
        "compiled-executable store.",
    )
    ap.add_argument("command", choices=("ls", "verify", "gc"))
    ap.add_argument("cache_dir", help="store directory (e.g. logs/<name>/compile_cache)")
    ap.add_argument("--keep-last", type=int, default=0,
                    help="gc: keep only the newest K entries")
    ap.add_argument("--max-age-days", type=float, default=None,
                    help="gc: drop entries older than D days")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)

    store = ExecutableStore(args.cache_dir)

    if args.command == "ls":
        rows = store.ls()
        if args.json:
            print(json.dumps({"entries": rows}))
        else:
            for r in rows:
                key = r.get("key") or {}
                bucket = "x".join(str(v) for v in (key.get("bucket") or ()))
                print(
                    f"{r['digest'][:12]}  {key.get('program', '?'):<16} "
                    f"bucket={bucket:<14} {key.get('backend', '?'):<5} "
                    f"{r.get('exe_format', '?'):<9} {r.get('bytes', 0)} B  "
                    f"{r.get('created_utc') or '-'}"
                )
            print(f"{len(rows)} entr{'y' if len(rows) == 1 else 'ies'}")
        return 0

    if args.command == "verify":
        reports = store.verify()
        bad = [r for r in reports if not r.get("ok")]
        if args.json:
            print(json.dumps({"reports": reports, "ok": not bad}))
        else:
            for r in reports:
                status = (
                    f"ok ({r.get('exe_format')}, {r.get('bytes')} B)"
                    if r.get("ok")
                    else f"CORRUPT: {r.get('error')}"
                )
                print(f"{r['file']}: {status}")
        return 1 if bad else 0

    removed = store.gc(keep_last=args.keep_last, max_age_days=args.max_age_days)
    if args.json:
        print(json.dumps({"removed": removed}))
    else:
        for digest in removed:
            print(f"removed: {digest}")
        print(f"{len(removed)} removed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
