"""Committed violation baseline for graftlint.

The baseline exists so the linter can be adopted mid-project without a
flag-day: known violations are recorded here (by line-number-free key,
``path::qualname::rule``) and tolerated, while any NEW violation fails
loudly. Policy (enforced by tests/test_lint_clean.py + ISSUE 4): the
baseline must stay EMPTY for ``host-sync-in-step`` and ``cond-in-guard`` —
those two invariants are load-bearing for correctness (per-step host round
trips, guard bit-inertness) and are never grandfathered.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from .graftlint import Report, Violation

# Rules that may never carry baseline entries. unguarded-shared-write joins
# the original two (ISSUE 8): a grandfathered lost-update race corrupts
# counters/caches silently — it must be fixed or inline-suppressed with a
# reason, never tolerated by count. collective-divergence and
# torn-state-hazard join them (ISSUE 19): a grandfathered rank-divergent
# collective deadlocks the first real multi-host mesh, and a grandfathered
# torn-state window silently corrupts every crash recovery after it.
NO_BASELINE_RULES = (
    "host-sync-in-step",
    "cond-in-guard",
    "unguarded-shared-write",
    "collective-divergence",
    "torn-state-hazard",
)

DEFAULT_BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json"
)


def load_baseline(path: str = DEFAULT_BASELINE_PATH) -> Dict[str, int]:
    """key -> tolerated count. A missing file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    entries = doc.get("entries", {})
    bad = [
        key
        for key in entries
        if any(key.endswith("::" + rule) for rule in NO_BASELINE_RULES)
    ]
    if bad:
        raise ValueError(
            f"baseline carries entries for never-grandfathered rules: {bad}"
        )
    return {str(k): int(v) for k, v in entries.items()}


def save_baseline(
    report: Report,
    path: str = DEFAULT_BASELINE_PATH,
    preserve: Optional[Dict[str, int]] = None,
) -> Dict[str, int]:
    """Write the report's violations as the new baseline (refusing the
    never-grandfathered rules — those must be fixed, not recorded).

    ``preserve`` carries existing entries to keep verbatim: a single-pass
    ``--update-baseline`` (``trace``, or ``lint --no-trace``) must not
    clobber the OTHER pass's grandfathered entries in the shared file."""
    entries: Dict[str, int] = dict(preserve or {})
    refused: List[Violation] = []
    for v in report.violations:
        if v.rule in NO_BASELINE_RULES:
            refused.append(v)
        else:
            entries[v.key] = entries.get(v.key, 0) + 1
    if refused:
        raise ValueError(
            "refusing to baseline "
            + "; ".join(v.format() for v in refused[:5])
            + " — fix these, they are never grandfathered"
        )
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1, sort_keys=True)
        f.write("\n")
    return entries


def new_violations(
    report: Report, baseline: Dict[str, int]
) -> List[Violation]:
    """Violations not covered by the baseline (per-key counts respected:
    a file that grows a second instance of a baselined violation fails)."""
    budget = dict(baseline)
    out: List[Violation] = []
    for v in report.violations:
        if budget.get(v.key, 0) > 0:
            budget[v.key] -= 1
        else:
            out.append(v)
    return out
