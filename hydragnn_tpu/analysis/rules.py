"""graftlint rule catalogue + the framework knowledge the rules key off.

Every rule guards an invariant this framework PAID to establish and that
nothing mechanical checked before this module existed (docs/STATIC_ANALYSIS.md
has the full catalogue with examples):

* ``host-sync-in-step``   — no host synchronization inside code reachable from
  a compiled step body (trainer._step_body, the scan/shard_map paths, the
  serve worker's jitted forward). A ``.item()`` / ``np.asarray`` / ``float()``
  on a traced value either fails at trace time or — worse — silently forces a
  device round-trip per step when the function also runs eagerly.
* ``cond-in-guard``       — the non-finite step guard must stay bit-inert:
  ``jnp.where`` selects, never ``lax.cond`` (a conditional region moves XLA's
  fusion boundaries; the clean path then stops being bit-identical to the
  unguarded build — measured, trainer._keep_if's docstring).
* ``use-after-donate``    — a buffer passed at a donated position of a
  ``donate_argnums`` callable is dead; reading it afterwards is undefined
  behavior that XLA only sometimes reports.
* ``recompile-hazard``    — patterns that silently multiply compiles:
  jnp work at module import time, jit-wrapper construction inside a loop,
  unhashable literals fed to static args.
* ``nondeterminism``      — wall-clock / global-RNG entropy in traced code or
  in the collation path (collation must be a pure function of (dataset, seed,
  epoch) for the resume/replay contracts to hold).

``suppression-without-reason`` is the meta-rule: every inline
``# graftlint: disable=<rule>(<reason>)`` must carry a justification string.

The ``graftrace`` half (analysis/concurrency.py) adds the host-concurrency
rules over the same catalogue — the five cooperating thread roots
(prefetch/transfer pipeline, serve batcher+dispatcher+HTTP handlers,
checkpoint writer, supervisor loop) share counters, caches, and manifests
that nothing mechanical checked before:

* ``missing-guard-decl``      — an attribute written from >= 2 thread roots
  carries no ``# guarded-by: <lock>`` declaration.
* ``unguarded-shared-write``  — a write to a guard-declared attribute outside
  a ``with <that lock>:`` block (never baselineable: a lost update corrupts
  counters/caches silently).
* ``guard-mismatch``          — an access to a guard-declared attribute under
  a different lock than declared, or an unlocked read without a
  ``dirty-reads`` clause in the declaration.
* ``lock-order-inversion``    — the static lock-order graph has a cycle
  (two threads can acquire the same pair of locks in opposite orders).
* ``blocking-queue-in-lock``  — an unbounded blocking operation
  (queue get/put/join, Event.wait, Thread.join) reachable while a lock is
  held: the classic convoy/deadlock shape.
* ``fork-after-threads``      — ``os.fork`` / fork-context multiprocessing in
  a package that starts threads (a forked child inherits locked locks).
* ``jax-dispatch-off-main``   — JAX dispatch from a thread root outside the
  sanctioned DeviceFeed transfer / serve dispatch paths.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str


RULES = {
    r.id: r
    for r in (
        Rule(
            "host-sync-in-step",
            "host-sync call (.item()/.tolist()/float()/np.asarray/"
            "jax.device_get/block_until_ready) in code reachable from a "
            "compiled step body",
        ),
        Rule(
            "cond-in-guard",
            "lax.cond/lax.switch or Python branching on the all-finite flag "
            "in guard-path code — the guard must stay bit-inert (jnp.where)",
        ),
        Rule(
            "use-after-donate",
            "read of a buffer after it was passed at a donated position of a "
            "donate_argnums callable",
        ),
        Rule(
            "recompile-hazard",
            "silent compile multiplier: jnp work at import time, jit "
            "construction inside a loop, unhashable static-arg literal",
        ),
        Rule(
            "nondeterminism",
            "wall-clock or global-RNG entropy in traced or "
            "collation-deterministic code",
        ),
        Rule(
            "suppression-without-reason",
            "graftlint suppression comment without a justification string",
        ),
        # ------------------------------------------------ graftrace (concurrency)
        Rule(
            "missing-guard-decl",
            "attribute written from >= 2 thread roots without a "
            "'# guarded-by: <lock>' declaration",
        ),
        Rule(
            "unguarded-shared-write",
            "write to a guard-declared shared attribute outside a "
            "'with <declared lock>:' block",
        ),
        Rule(
            "guard-mismatch",
            "access to a guard-declared attribute under the wrong lock, or "
            "an unlocked read without a dirty-reads clause",
        ),
        Rule(
            "lock-order-inversion",
            "cycle in the static lock-order graph (potential deadlock)",
        ),
        Rule(
            "blocking-queue-in-lock",
            "unbounded blocking operation (queue get/put/join, Event.wait, "
            "Thread.join) reachable while holding a lock",
        ),
        Rule(
            "fork-after-threads",
            "os.fork / fork-context multiprocessing in a thread-spawning "
            "package (forked children inherit held locks)",
        ),
        Rule(
            "jax-dispatch-off-main",
            "JAX dispatch from a thread root outside the sanctioned "
            "DeviceFeed transfer / serve dispatch paths",
        ),
        # ------------------------------------------------ graftproto (protocol)
        Rule(
            "collective-divergence",
            "rank-dependent branch, or a branch whose arms trace different "
            "collective sequences, inside compiled/lockstep code — ranks "
            "would issue mismatched collectives and the mesh deadlocks",
        ),
        Rule(
            "barrier-divergence",
            "members of one lockstep segment reach different named-barrier "
            "sequences — the rendezvous round can never complete",
        ),
        Rule(
            "barrier-under-lock",
            "rendezvous barrier reached while holding a lock another thread "
            "root acquires — a distributed convoy/deadlock shape",
        ),
        Rule(
            "leader-only-barrier",
            "rendezvous barrier inside a rank-guarded branch — followers "
            "never arrive and the leader blocks until the round times out",
        ),
        Rule(
            "torn-state-hazard",
            "persistence write in control-plane state code that is not "
            "atomic-rename-shaped (or a multi-file update without a single "
            "authoritative install) — a crash tears the recovered state",
        ),
        # ------------------------------------------------ graftlint additions
        Rule(
            "pickle-load-outside-compat",
            "pickle.load/pickle.loads/torch.load outside the sanctioned "
            "v1-compat shims — the raw-pickle read path was deprecated in "
            "PR 16 (GSHD convert CLI); new call sites are regressions",
        ),
    )
}

# Rule ids owned by the graftrace concurrency pass (analysis/concurrency.py);
# everything else in RULES is the graftlint pass's.
CONCURRENCY_RULES = frozenset(
    {
        "missing-guard-decl",
        "unguarded-shared-write",
        "guard-mismatch",
        "lock-order-inversion",
        "blocking-queue-in-lock",
        "fork-after-threads",
        "jax-dispatch-off-main",
    }
)

# Rule ids owned by the graftproto protocol pass (analysis/proto.py). The
# three passes (lint / trace / proto) partition RULES so their baseline
# updates never clobber each other's keys (__main__.py preserve logic).
PROTO_RULES = frozenset(
    {
        "collective-divergence",
        "barrier-divergence",
        "barrier-under-lock",
        "leader-only-barrier",
        "torn-state-hazard",
    }
)


# --------------------------------------------------------------- framework map
# Factories whose NESTED function definitions are compiled step bodies even
# though the jit/scan wrapping happens at the call site (trainer.py's
# ``_step_body`` returns ``body``; make_train_step jits it later). Static
# call-graph analysis cannot see through the closure return, so the linter is
# told directly.
TRACED_FACTORIES = frozenset(
    {
        "_step_body",
        "make_train_step",
        "make_eval_step",
        "make_train_epoch_scan",
        "make_train_step_dp",
        "make_eval_step_dp",
    }
)

# Callables that return a donating compiled step (donate_argnums=(0,)):
# calling one binds a callable whose argument 0 buffer set is consumed.
DONATING_FACTORIES = {
    "make_train_step": (0,),
    "make_train_step_dp": (0,),
    "make_train_epoch_scan": (0,),
}

# jax transforms whose callable arguments become traced roots.
TRANSFORM_ENTRY_POINTS = frozenset(
    {
        "jax.jit",
        "jit",
        "jax.pmap",
        "jax.vmap",
        "vmap",
        "jax.grad",
        "jax.value_and_grad",
        "jax.checkpoint",
        "jax.remat",
        "jax.eval_shape",
        "jax.lax.scan",
        "lax.scan",
        "jax.lax.while_loop",
        "lax.while_loop",
        "jax.lax.fori_loop",
        "lax.fori_loop",
        "jax.lax.cond",
        "lax.cond",
        "jax.lax.switch",
        "lax.switch",
        "shard_map",
        "jax.experimental.shard_map.shard_map",
        "pl.pallas_call",
        "pallas_call",
    }
)

# Module-path substrings whose TRACED functions form the guard path — the
# bit-inertness invariant scope for ``cond-in-guard``.
GUARD_PATH_MODULES = ("train/trainer.py",)
# Functions that are guard-path regardless of module (helpers the guard owns).
GUARD_PATH_FUNCTIONS = frozenset({"_keep_if", "_all_finite"})

# Module-path substrings where collation/splitting determinism is contractual:
# batches must be a pure function of (dataset, seed, epoch) or crash-resume
# replay and the device-cache epochs diverge from the streamed path.
COLLATION_DETERMINISTIC_MODULES = (
    "graphs/collate.py",
    "graphs/batch.py",
    "graphs/csr.py",
    "graphs/sample.py",
    "graphs/packing.py",
    "preprocess/dataloader.py",
    "preprocess/splitting.py",
    # The streaming data plane: shard encoding and the epoch plan must be
    # wall-clock-free (byte-identical conversion, bit-exact streamed epochs
    # — docs/DATA_PLANE.md).
    "datasets/shards.py",
    "datasets/stream.py",
)

# Host-sync call patterns (attribute tails / dotted names / builtins).
HOST_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
HOST_SYNC_DOTTED = frozenset(
    {
        "jax.device_get",
        "jax.block_until_ready",
        "np.asarray",
        "np.array",
        "numpy.asarray",
        "numpy.array",
    }
)
HOST_SYNC_BUILTINS = frozenset({"float", "int", "bool"})

# np.random attributes that are fine (explicitly-seeded generator plumbing).
SEEDED_NP_RANDOM = frozenset(
    {"default_rng", "Generator", "SeedSequence", "PCG64", "Philox"}
)


# ----------------------------------------------------- graftrace framework map
# The implicit main thread every entry point runs on.
MAIN_THREAD_ROOT = "main"

# Framework callables whose callable/iterable ARGUMENTS run on pipeline
# threads even though no ``threading.Thread(target=...)`` is visible at the
# call site (train/pipeline.py's two-stage feed): position/keyword -> the
# thread root the bound callable executes on. The same blindness
# TRACED_FACTORIES fixes for tracedness, fixed for runs-on-thread.
THREAD_CALLABLE_BINDINGS = {
    "DeviceFeed": {0: "feed-host", "iterable": "feed-host",
                   1: "feed-transfer", "transfer": "feed-transfer"},
    "_Prefetcher": {0: "feed-host", "iterable": "feed-host"},
    # The streaming loader's decode-ahead ring (datasets/stream.py): the
    # decode callable runs on the "hydragnn-shard-prefetch" daemon thread.
    # It must stay jax-free — decoded shards are host numpy; device work
    # happens downstream on the sanctioned transfer stage.
    "ShardRing": {1: "shard-prefetch", "decode": "shard-prefetch"},
}

# Factories whose NESTED function definitions run on a pipeline thread (the
# returned closure is installed as a DeviceFeed transfer stage; static
# analysis cannot see through the return, exactly like TRACED_FACTORIES).
THREAD_FACTORY_ROOTS = {
    "with_transfer_retries": "feed-transfer",
}

# Classes whose subclasses' methods run on per-connection server threads.
HTTP_HANDLER_BASES = frozenset({"BaseHTTPRequestHandler"})
HTTP_HANDLER_ROOT = "http-handler"

# Thread roots allowed to dispatch JAX work. Everything host-side must stay
# jax-free: the checkpoint writer thread serializes already-snapshotted host
# numpy, the batcher collates with numpy, HTTP handlers only block on
# futures. The transfer stage and the serve dispatcher ARE the sanctioned
# device paths (docs/INPUT_PIPELINE.md, docs/SERVING.md).
SANCTIONED_DISPATCH_ROOTS = frozenset(
    {MAIN_THREAD_ROOT, "feed-transfer", "hydragnn-serve-dispatch"}
)

# Dotted call prefixes that dispatch device work when executed.
JAX_DISPATCH_CALLS = frozenset(
    {
        "jax.device_put",
        "jax.device_get",
        "jax.block_until_ready",
        "jax.jit",
        "jax.pmap",
        "jax.eval_shape",
    }
)
JAX_DISPATCH_PREFIXES = ("jax.numpy.", "jax.lax.", "jax.random.")

# Attribute types that synchronize themselves — writes THROUGH them need no
# guard (the binding write of the attribute cell itself still does, when it
# happens outside __init__).
THREAD_SAFE_TYPES = frozenset(
    {
        "queue.Queue",
        "queue.SimpleQueue",
        "queue.LifoQueue",
        "queue.PriorityQueue",
        "threading.Lock",
        "threading.RLock",
        "threading.Condition",
        "threading.Event",
        "threading.Semaphore",
        "threading.BoundedSemaphore",
        "threading.local",
        "collections.deque",
    }
)

# Container-mutator method names: ``self.X.append(...)`` mutates X.
MUTATOR_METHODS = frozenset(
    {
        "append", "extend", "insert", "add", "discard", "remove", "pop",
        "popitem", "clear", "update", "setdefault", "sort", "reverse",
    }
)

# Unbounded blocking calls by receiver type (graftrace types attributes from
# their __init__ construction): method names that park the calling thread.
BLOCKING_METHODS_BY_TYPE = {
    "queue.Queue": ("put", "get", "join"),
    "queue.LifoQueue": ("put", "get", "join"),
    "queue.PriorityQueue": ("put", "get", "join"),
    "queue.SimpleQueue": ("put", "get"),
    "threading.Event": ("wait",),
    "threading.Condition": ("wait", "wait_for"),
    "threading.Thread": ("join",),
}

# Process-fork entry points (fork-after-threads). subprocess.* is fork+exec
# and safe; multiprocessing with an explicit "spawn"/"forkserver" context is
# exempted at the call site.
FORK_CALLS = frozenset({"os.fork", "os.forkpty", "pty.fork"})
MP_PROCESS_CALLS = frozenset(
    {"multiprocessing.Process", "multiprocessing.Pool"}
)


# ----------------------------------------------------- graftproto framework map
# Collective call name tails: a call whose dotted tail is one of these (with
# or without the jax.lax/lax prefix) participates in the mesh's lockstep
# collective sequence. Ranks must trace IDENTICAL sequences or the XLA
# program deadlocks on a real multi-host mesh.
COLLECTIVE_CALLS = frozenset(
    {
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "ppermute",
        "pshuffle",
        "all_gather",
        "all_to_all",
        "axis_index",
    }
)
# Names whose truthiness encodes rank identity: branching on one inside
# traced or lockstep code makes different ranks take different paths.
RANK_GUARD_NAMES = frozenset(
    {
        "rank",
        "shard_rank",
        "worker_rank",
        "process_index",
        "host_id",
        "is_leader",
        "leader",
    }
)

# Framework callables whose callable ARGUMENT runs as every member of a
# lockstep segment (run_workers spawns one thread per rank, all executing the
# bound fn with f-string thread names static analysis cannot read): the
# runs-on-thread analog of THREAD_CALLABLE_BINDINGS for the mesh harness.
# position/keyword -> the lockstep segment name the bound callable joins.
LOCKSTEP_CALLABLE_BINDINGS = {
    "run_workers": {1: "mesh-worker", "fn": "mesh-worker"},
}

# Rendezvous-barrier funnel methods: Class.method pairs that IMPLEMENT the
# barrier protocol (they are the barrier, not users of it) — their bodies are
# exempt from the barrier-protocol rules.
BARRIER_FUNNEL_METHODS = frozenset(
    {
        ("LoopbackRendezvous", "barrier"),
        ("ProxyRendezvous", "barrier"),
        ("LoopbackWorker", "barrier"),
        ("LoopbackRendezvous", "exchange"),
        ("LoopbackRendezvous", "broadcast"),
        ("ProxyRendezvous", "exchange"),
        ("ProxyRendezvous", "broadcast"),
        ("ProxyRendezvous", "allgather"),
    }
)

# Atomic persistence funnels: call tails that ARE the atomic-rename install
# (checkpoint/io.py's tmp+fsync+os.replace shapes). Control-plane state must
# flow through one of these; a bare open(path,"w")/shutil copy in a
# PERSISTENCE_STATE_MODULES function that never os.replace()s is a
# torn-state-hazard.
PERSISTENCE_CALLS = frozenset(
    {
        "atomic_write_json",
        "write_checkpoint_blob",
        "atomic_copy_file",
    }
)
# Module-path substrings whose functions hold crash-recovered control-plane
# state (the incarnation contract's scope). Telemetry/bench/dataset writers
# outside these paths are free to stream to open files.
PERSISTENCE_STATE_MODULES = (
    "checkpoint/io.py",
    "checkpoint/async_writer.py",
    "lifecycle/registry.py",
    "lifecycle/manager.py",
    "flywheel/loop.py",
    "parallel/elastic.py",
)
# Function names inside PERSISTENCE_STATE_MODULES that IMPLEMENT the atomic
# funnels (the open(tmp,"wb") + os.replace inside them is the mechanism, not
# a hazard).
PERSISTENCE_FUNNEL_FUNCTIONS = frozenset(
    {
        "atomic_write_json",
        "write_checkpoint_blob",
        "atomic_copy_file",
        "_unique_tmp",
    }
)

# Raw-deserialization entry points (pickle-load-outside-compat): the GSHD
# digest-verified containers replaced these in PR 16; surviving call sites
# are sanctioned v1-compat shims and carry reasoned suppressions.
PICKLE_LOAD_CALLS = frozenset(
    {
        "pickle.load",
        "pickle.loads",
        "torch.load",
    }
)
