"""graftlint rule catalogue + the framework knowledge the rules key off.

Every rule guards an invariant this framework PAID to establish and that
nothing mechanical checked before this module existed (docs/STATIC_ANALYSIS.md
has the full catalogue with examples):

* ``host-sync-in-step``   — no host synchronization inside code reachable from
  a compiled step body (trainer._step_body, the scan/shard_map paths, the
  serve worker's jitted forward). A ``.item()`` / ``np.asarray`` / ``float()``
  on a traced value either fails at trace time or — worse — silently forces a
  device round-trip per step when the function also runs eagerly.
* ``cond-in-guard``       — the non-finite step guard must stay bit-inert:
  ``jnp.where`` selects, never ``lax.cond`` (a conditional region moves XLA's
  fusion boundaries; the clean path then stops being bit-identical to the
  unguarded build — measured, trainer._keep_if's docstring).
* ``use-after-donate``    — a buffer passed at a donated position of a
  ``donate_argnums`` callable is dead; reading it afterwards is undefined
  behavior that XLA only sometimes reports.
* ``recompile-hazard``    — patterns that silently multiply compiles:
  jnp work at module import time, jit-wrapper construction inside a loop,
  unhashable literals fed to static args.
* ``nondeterminism``      — wall-clock / global-RNG entropy in traced code or
  in the collation path (collation must be a pure function of (dataset, seed,
  epoch) for the resume/replay contracts to hold).

``suppression-without-reason`` is the meta-rule: every inline
``# graftlint: disable=<rule>(<reason>)`` must carry a justification string.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str


RULES = {
    r.id: r
    for r in (
        Rule(
            "host-sync-in-step",
            "host-sync call (.item()/.tolist()/float()/np.asarray/"
            "jax.device_get/block_until_ready) in code reachable from a "
            "compiled step body",
        ),
        Rule(
            "cond-in-guard",
            "lax.cond/lax.switch or Python branching on the all-finite flag "
            "in guard-path code — the guard must stay bit-inert (jnp.where)",
        ),
        Rule(
            "use-after-donate",
            "read of a buffer after it was passed at a donated position of a "
            "donate_argnums callable",
        ),
        Rule(
            "recompile-hazard",
            "silent compile multiplier: jnp work at import time, jit "
            "construction inside a loop, unhashable static-arg literal",
        ),
        Rule(
            "nondeterminism",
            "wall-clock or global-RNG entropy in traced or "
            "collation-deterministic code",
        ),
        Rule(
            "suppression-without-reason",
            "graftlint suppression comment without a justification string",
        ),
    )
}


# --------------------------------------------------------------- framework map
# Factories whose NESTED function definitions are compiled step bodies even
# though the jit/scan wrapping happens at the call site (trainer.py's
# ``_step_body`` returns ``body``; make_train_step jits it later). Static
# call-graph analysis cannot see through the closure return, so the linter is
# told directly.
TRACED_FACTORIES = frozenset(
    {
        "_step_body",
        "make_train_step",
        "make_eval_step",
        "make_train_epoch_scan",
        "make_train_step_dp",
        "make_eval_step_dp",
    }
)

# Callables that return a donating compiled step (donate_argnums=(0,)):
# calling one binds a callable whose argument 0 buffer set is consumed.
DONATING_FACTORIES = {
    "make_train_step": (0,),
    "make_train_step_dp": (0,),
    "make_train_epoch_scan": (0,),
}

# jax transforms whose callable arguments become traced roots.
TRANSFORM_ENTRY_POINTS = frozenset(
    {
        "jax.jit",
        "jit",
        "jax.pmap",
        "jax.vmap",
        "vmap",
        "jax.grad",
        "jax.value_and_grad",
        "jax.checkpoint",
        "jax.remat",
        "jax.eval_shape",
        "jax.lax.scan",
        "lax.scan",
        "jax.lax.while_loop",
        "lax.while_loop",
        "jax.lax.fori_loop",
        "lax.fori_loop",
        "jax.lax.cond",
        "lax.cond",
        "jax.lax.switch",
        "lax.switch",
        "shard_map",
        "jax.experimental.shard_map.shard_map",
        "pl.pallas_call",
        "pallas_call",
    }
)

# Module-path substrings whose TRACED functions form the guard path — the
# bit-inertness invariant scope for ``cond-in-guard``.
GUARD_PATH_MODULES = ("train/trainer.py",)
# Functions that are guard-path regardless of module (helpers the guard owns).
GUARD_PATH_FUNCTIONS = frozenset({"_keep_if", "_all_finite"})

# Module-path substrings where collation/splitting determinism is contractual:
# batches must be a pure function of (dataset, seed, epoch) or crash-resume
# replay and the device-cache epochs diverge from the streamed path.
COLLATION_DETERMINISTIC_MODULES = (
    "graphs/collate.py",
    "graphs/batch.py",
    "graphs/csr.py",
    "graphs/sample.py",
    "graphs/packing.py",
    "preprocess/dataloader.py",
    "preprocess/splitting.py",
)

# Host-sync call patterns (attribute tails / dotted names / builtins).
HOST_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})
HOST_SYNC_DOTTED = frozenset(
    {
        "jax.device_get",
        "jax.block_until_ready",
        "np.asarray",
        "np.array",
        "numpy.asarray",
        "numpy.array",
    }
)
HOST_SYNC_BUILTINS = frozenset({"float", "int", "bool"})

# np.random attributes that are fine (explicitly-seeded generator plumbing).
SEEDED_NP_RANDOM = frozenset(
    {"default_rng", "Generator", "SeedSequence", "PCG64", "Philox"}
)
