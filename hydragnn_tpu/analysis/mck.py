"""graftproto's runtime half: a crash-consistency model checker for the
distributed control plane (``python -m hydragnn_tpu.analysis modelcheck``).

The fault drills (ELASTIC_r15 / SWAP_r13 / FLYWHEEL_r17) each kill the
process at ONE hand-picked point — the save, the promote persist, the
pre-persist hook. This module generalizes the tsan seeded-schedule idea to
crash schedules: every atomic persistence funnel
(:func:`~hydragnn_tpu.checkpoint.io.atomic_write_json`,
:func:`~hydragnn_tpu.checkpoint.io.write_checkpoint_blob`,
:func:`~hydragnn_tpu.checkpoint.io.atomic_copy_file`) is intercepted, the
control-plane scenarios are run once to RECORD which persistence points they
actually reach (auto-discovery — nothing is hand-picked), and then each
scenario is re-run once per (point, mode) with a fault injected there:

* ``kill`` — :class:`CrashInjected` (a ``BaseException``, so no
  ``except Exception`` in the code under test can absorb it) raised BEFORE
  the atomic install: the bytes must simply not exist afterwards.
* ``exception`` — the install completes, then a ``RuntimeError`` aborts the
  caller mid-flight: the bytes ARE durable but every in-memory step after
  the install was lost.

After every injection, recovery runs from DISK ONLY (a fresh
:class:`~hydragnn_tpu.lifecycle.registry.ModelRegistry`, a fresh
:func:`~hydragnn_tpu.checkpoint.io.load_verified_chain`) and the standing
invariants are asserted:

* **roles untorn** — the lifecycle sidecar parses, and every role it names
  resolves to an intact, digest-verified file of a KNOWN version;
* **restore ∈ save_log** — the recovered training step is one the scenario
  actually attempted to save, and no completed save is ever lost
  (monotonicity);
* **sample-multiset conservation** — the elastic resume descriptor, resharded
  to the new world size, schedules every remaining batch exactly once;
* **quarantine integrity** — a rejected candidate's forensic copy is either
  absent or byte-identical to the source, never torn.

Determinism: the injection schedule is ordered by
``sha256(f"{seed}:{scenario}:{point}:{mode}")`` (same construction as the
tsan drill's seeded scheduler) and the whole schedule is fingerprinted as
``schedule_sha256`` — two runs with the same seed must match, which
tests/test_proto_lint.py pins as the determinism witness.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["CrashInjected", "model_check", "SCENARIOS", "SMOKE_SCENARIOS"]


class CrashInjected(BaseException):
    """A simulated SIGKILL at a persistence point. Deliberately a
    ``BaseException``: the code under test is full of honest
    ``except Exception`` recovery blocks, and a real SIGKILL is not
    catchable — the simulation must not be either."""


# Crash points the existing drills already cover with hand-picked kills
# (ELASTIC_r15 kills at save, SWAP_r13/FLYWHEEL_r17 kill around the promote
# persist via the pre-persist hook). Everything else the checker discovers
# is NEW coverage — ANALYSIS_r19.json reports the delta.
KNOWN_DRILLED_POINTS = frozenset(
    {
        "write_checkpoint_blob@save_model",
        "atomic_write_json@_persist<commit_promote",
        "atomic_write_json@_persist<commit_rollback",
    }
)

_FUNNEL_NAMES = ("atomic_write_json", "write_checkpoint_blob", "atomic_copy_file")


# --------------------------------------------------------------- interception
@dataclass
class _Injector:
    """One armed fault (or a recording pass when ``mode == 'record'``)."""

    mode: str  # "record" | "kill" | "exception"
    target: Optional[str] = None
    # A point reached N times in a scenario (e.g. two saves through
    # write_checkpoint_blob@save_model) yields N injections — crashing the
    # SECOND save is the case that proves the first survives.
    target_occurrence: int = 0
    fired: bool = False
    recorded: List[str] = field(default_factory=list)
    seen: Dict[str, int] = field(default_factory=dict)


_CURRENT: Optional[_Injector] = None


def _point_id(funnel: str) -> str:
    """Identity of the persistence point = which funnel, called from which
    function. ``ModelRegistry._persist`` is a fan-in (five role flips all
    persist through it), so its points carry the grand-caller too:
    ``atomic_write_json@_persist<commit_promote``."""
    frame = sys._getframe(2)  # skip _point_id + the wrapper
    names: List[str] = []
    while frame is not None and len(names) < 2:
        code = frame.f_code
        path = code.co_filename.replace(os.sep, "/")
        if "hydragnn_tpu" in path and "/analysis/mck" not in path:
            names.append(code.co_name)
        frame = frame.f_back
    caller = names[0] if names else "<external>"
    point = f"{funnel}@{caller}"
    if caller == "_persist" and len(names) > 1:
        point += f"<{names[1]}"
    return point


def _wrap(funnel: str, orig: Callable[..., Any]) -> Callable[..., Any]:
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        inj = _CURRENT
        if inj is None:
            return orig(*args, **kwargs)
        point = _point_id(funnel)
        if inj.mode == "record":
            inj.recorded.append(point)
            return orig(*args, **kwargs)
        if point == inj.target and not inj.fired:
            occ = inj.seen.get(point, 0)
            inj.seen[point] = occ + 1
            if occ == inj.target_occurrence:
                inj.fired = True
                if inj.mode == "kill":
                    raise CrashInjected(point)
                orig(*args, **kwargs)
                raise RuntimeError(f"mck post-install fault at {point}")
        return orig(*args, **kwargs)

    wrapper.__name__ = f"_mck_{funnel}"
    return wrapper


class _Patched:
    """Context manager installing the funnel wrappers. Besides the
    ``checkpoint.io`` module attributes, ``lifecycle/registry.py`` imports
    ``atomic_write_json`` BY NAME at import time, so its module global is
    rebound too (and restored on exit)."""

    def __enter__(self) -> "_Patched":
        from ..checkpoint import io as ckpt_io
        from ..lifecycle import registry as lifecycle_registry

        self._io = ckpt_io
        self._registry = lifecycle_registry
        self._saved_io = {n: getattr(ckpt_io, n) for n in _FUNNEL_NAMES}
        self._saved_reg = lifecycle_registry.atomic_write_json
        for n, orig in self._saved_io.items():
            setattr(ckpt_io, n, _wrap(n, orig))
        lifecycle_registry.atomic_write_json = _wrap(
            "atomic_write_json", self._saved_reg
        )
        return self

    def __exit__(self, *exc: Any) -> None:
        for n, orig in self._saved_io.items():
            setattr(self._io, n, orig)
        self._registry.atomic_write_json = self._saved_reg


# ------------------------------------------------------------------ scenarios
def _variables(fill: float) -> Dict[str, Any]:
    import numpy as np

    return {
        "params": {
            "dense": {
                "kernel": np.full((2, 3), fill, dtype=np.float32),
                "bias": np.zeros((3,), dtype=np.float32),
            }
        }
    }


@dataclass
class _Ctx:
    """Per-injection world: a fresh directory plus the scenario's honest
    save log (``attempts`` appended BEFORE each save call, ``completed``
    after it returns — the durable-but-aborted ``exception`` mode lands in
    the gap between the two)."""

    tmp: str
    name: str = "mck_model"
    attempts: List[int] = field(default_factory=list)
    completed: List[int] = field(default_factory=list)
    valid_versions: List[str] = field(default_factory=list)
    quarantine_src: Optional[str] = None
    quarantine_dst: Optional[str] = None

    @property
    def run_dir(self) -> str:
        # save_model(path=tmp, name=name) writes into <tmp>/<name>/
        return os.path.join(self.tmp, self.name)


def _save(ctx: _Ctx, fill: float, step: int, *, world: int = 4,
          epoch: int = 1, cursor: int = 3, num_batches: int = 8) -> str:
    from ..checkpoint.io import elastic_handoff_meta, save_model

    meta = {
        "epoch": epoch,
        "elastic": elastic_handoff_meta(
            world_size=world,
            epoch=epoch,
            cursor=cursor,
            incarnation=0,
            global_step=step,
            num_batches=num_batches,
        ),
    }
    ctx.attempts.append(step)
    save_model(
        _variables(fill), None, ctx.name, path=ctx.tmp, meta=meta,
        keep_last_k=2,
    )
    ctx.completed.append(step)
    return os.path.join(ctx.run_dir, ctx.name + ".pk")


def _scenario_elastic(ctx: _Ctx) -> None:
    """Two elastic saves at world 4 (step 100 then 200): the checkpoint the
    shrink-to-world-2 restore hands off from. A crash at the second save
    must recover the first, byte-intact."""
    _save(ctx, 1.0, 100, epoch=1, cursor=3)
    _save(ctx, 2.0, 200, epoch=2, cursor=5)


def _scenario_swap_promote(ctx: _Ctx) -> None:
    from ..lifecycle.registry import ModelRegistry

    p1 = _save(ctx, 1.0, 100, epoch=1)
    reg = ModelRegistry(ctx.run_dir, ctx.name)
    reg.set_live(p1)
    ctx.valid_versions.append(reg.live.version)
    p2 = _save(ctx, 2.0, 200, epoch=2)
    ctx.valid_versions.append(reg.identify(p2).version)
    mv = reg.stage_candidate()
    reg.commit_promote(mv)


def _scenario_swap_rollback(ctx: _Ctx) -> None:
    from ..lifecycle.registry import ModelRegistry

    p1 = _save(ctx, 1.0, 100, epoch=1)
    reg = ModelRegistry(ctx.run_dir, ctx.name)
    reg.set_live(p1)
    old = reg.live
    ctx.valid_versions.append(old.version)
    p2 = _save(ctx, 2.0, 200, epoch=2)
    ctx.valid_versions.append(reg.identify(p2).version)
    mv = reg.stage_candidate()
    reg.commit_promote(mv)
    reg.commit_rollback(old)


def _scenario_flywheel_staging(ctx: _Ctx) -> None:
    """The flywheel rejection path: stage → quarantine the bytes (through
    the REAL ``Flywheel._quarantine``, driven unbound on a stub so the
    forensic copy exercises the exact shipping code) → clear the candidate."""
    from ..flywheel.loop import Flywheel
    from ..lifecycle.registry import ModelRegistry

    p1 = _save(ctx, 1.0, 100, epoch=1)
    reg = ModelRegistry(ctx.run_dir, ctx.name)
    reg.set_live(p1)
    ctx.valid_versions.append(reg.live.version)
    p2 = _save(ctx, 2.0, 200, epoch=2)
    ctx.valid_versions.append(reg.identify(p2).version)
    mv = reg.stage_candidate()
    stub = SimpleNamespace(
        run_dir=ctx.run_dir,
        config=SimpleNamespace(quarantine_dir="quarantine"),
    )
    ctx.quarantine_src = mv.path
    ctx.quarantine_dst = Flywheel._quarantine(stub, mv)
    if ctx.quarantine_dst is None:
        ctx.quarantine_dst = os.path.join(
            ctx.run_dir, "quarantine", f"{mv.short}.pk"
        )
    reg.clear_candidate(reason="mck: shadow gate red")


SCENARIOS: Dict[str, Callable[[_Ctx], None]] = {
    "elastic": _scenario_elastic,
    "swap_promote": _scenario_swap_promote,
    "swap_rollback": _scenario_swap_rollback,
    "flywheel_staging": _scenario_flywheel_staging,
}
# The CI smoke subset (static-analysis.yml): elastic shrink + swap promote.
SMOKE_SCENARIOS = ("elastic", "swap_promote")


# ------------------------------------------------------------------ recovery
def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _verify(ctx: _Ctx, new_world: int = 2) -> List[str]:
    """Recovery + invariants, from disk only. Returns failure strings."""
    from ..checkpoint.format import CheckpointError
    from ..checkpoint.io import load_verified_chain, verify_elastic_handoff
    from ..lifecycle.registry import ModelRegistry

    failures: List[str] = []

    # --- restore ∈ save_log + monotonicity -------------------------------
    meta: Optional[Dict[str, Any]] = None
    try:
        _vars, _opt, meta, _report = load_verified_chain(
            _variables(0.0), ctx.run_dir, ctx.name
        )
    except CheckpointError:
        if ctx.completed:
            failures.append(
                "restore: no checkpoint recoverable although "
                f"saves {ctx.completed} completed"
            )
    except FileNotFoundError:
        if ctx.completed:
            failures.append(
                f"restore: checkpoint files missing after {ctx.completed}"
            )
    if meta is not None:
        step = (meta.get("elastic") or {}).get("global_step")
        if step not in ctx.attempts:
            failures.append(
                f"restore: recovered step {step!r} was never saved "
                f"(attempts={ctx.attempts})"
            )
        elif ctx.completed and step < max(ctx.completed):
            failures.append(
                f"restore: recovered step {step} loses completed save "
                f"{max(ctx.completed)}"
            )
        # --- sample-multiset conservation across the world change --------
        try:
            resume = verify_elastic_handoff(meta, new_world)
        except CheckpointError as e:
            failures.append(f"handoff: {e}")
        else:
            cursor = resume["cursor"]
            num = (meta.get("elastic") or {}).get("num_batches", 0)
            remaining = list(range(cursor, num))
            scheduled = sorted(
                b
                for rank in range(new_world)
                for b in remaining[rank::new_world]
            )
            if scheduled != remaining:
                failures.append(
                    f"conservation: reshard to world {new_world} schedules "
                    f"{scheduled} != remaining {remaining}"
                )

    # --- roles untorn ----------------------------------------------------
    try:
        reg = ModelRegistry(ctx.run_dir, ctx.name)
        state = reg.state()
    except Exception as e:  # noqa: BLE001 — any load failure is a torn sidecar
        failures.append(f"roles: lifecycle sidecar unreadable ({e})")
    else:
        for role in ("live", "candidate", "previous"):
            doc = state["roles"].get(role)
            if not doc:
                continue
            try:
                mv = reg.identify(doc["path"])
            except Exception as e:  # noqa: BLE001
                failures.append(f"roles: {role} unverifiable ({e})")
                continue
            if ctx.valid_versions and mv.version not in ctx.valid_versions:
                failures.append(
                    f"roles: {role} carries unknown version {mv.short}"
                )

    # --- quarantine integrity --------------------------------------------
    if ctx.quarantine_dst and os.path.exists(ctx.quarantine_dst):
        if ctx.quarantine_src and os.path.exists(ctx.quarantine_src):
            if _sha256_file(ctx.quarantine_dst) != _sha256_file(
                ctx.quarantine_src
            ):
                failures.append(
                    "quarantine: forensic copy is torn (digest mismatch "
                    "with source)"
                )
    qdir = os.path.join(ctx.run_dir, "quarantine")
    if os.path.isdir(qdir):
        # a crash may leave a writer-owned .tmp — never a torn final file
        for f in os.listdir(qdir):
            if f.endswith(".pk") and ctx.quarantine_dst and os.path.join(
                qdir, f
            ) != ctx.quarantine_dst:
                failures.append(f"quarantine: unexpected final file {f}")
    return failures


# ------------------------------------------------------------------- driver
def _run_once(
    scenario: str, injector: Optional[_Injector]
) -> Tuple[str, List[str], _Ctx]:
    """One scenario execution in a fresh world. Returns
    (outcome, invariant_failures, ctx)."""
    global _CURRENT
    fn = SCENARIOS[scenario]
    with tempfile.TemporaryDirectory(prefix="mck_") as tmp:
        ctx = _Ctx(tmp=tmp)
        outcome = "completed"
        _CURRENT = injector
        try:
            fn(ctx)
        except CrashInjected:
            outcome = "crashed"
        except RuntimeError as e:
            outcome = (
                "faulted" if "mck post-install fault" in str(e) else "error"
            )
            if outcome == "error":
                raise
        finally:
            _CURRENT = None
        failures = _verify(ctx)
        return outcome, failures, ctx


def model_check(
    seed: int = 0,
    smoke: bool = False,
    scenarios: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """Enumerate crash injections at every auto-discovered persistence point
    and return the verdict document (``bench.py --analyze`` commits it into
    ANALYSIS_r19.json)."""
    names = list(
        scenarios
        if scenarios is not None
        else (SMOKE_SCENARIOS if smoke else SCENARIOS)
    )
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        raise ValueError(
            f"unknown scenario(s) {unknown}; known: {sorted(SCENARIOS)}"
        )

    with _Patched():
        # Pass 1: auto-discover the persistence points each scenario reaches.
        discovered: Dict[str, List[str]] = {}
        for name in names:
            rec = _Injector(mode="record")
            outcome, failures, _ctx = _run_once(name, rec)
            if outcome != "completed" or failures:
                return {
                    "ok": False,
                    "seed": seed,
                    "scenarios": names,
                    "failures": [
                        f"baseline {name}: outcome={outcome} {failures}"
                    ],
                    "points": [],
                    "injections": [],
                    "schedule_sha256": None,
                }
            discovered[name] = rec.recorded

        # Pass 2: the seeded crash schedule — one injection per
        # (scenario, point, OCCURRENCE, mode), ordered by the seed-keyed
        # digest. A point a scenario reaches twice (two saves through the
        # same funnel) is crashed at each visit: killing the second save is
        # what proves the first survives.
        plan: List[Tuple[str, str, int, str]] = []
        for name in names:
            counts: Dict[str, int] = {}
            for point in discovered[name]:
                occ = counts.get(point, 0)
                counts[point] = occ + 1
                for mode in ("kill", "exception"):
                    plan.append((name, point, occ, mode))
        plan.sort(
            key=lambda t: hashlib.sha256(
                f"{seed}:{t[0]}:{t[1]}:{t[2]}:{t[3]}".encode()
            ).hexdigest()
        )
        schedule = [
            {"scenario": s, "point": p, "occurrence": o, "mode": m}
            for s, p, o, m in plan
        ]
        schedule_sha256 = hashlib.sha256(
            json.dumps(schedule, sort_keys=True).encode()
        ).hexdigest()

        injections: List[Dict[str, Any]] = []
        failures: List[str] = []
        for name, point, occ, mode in plan:
            inj = _Injector(mode=mode, target=point, target_occurrence=occ)
            outcome, inv_failures, _ctx = _run_once(name, inj)
            if not inj.fired:
                inv_failures = inv_failures + [
                    f"schedule: point {point}#{occ} not reached on replay"
                ]
            injections.append(
                {
                    "scenario": name,
                    "point": point,
                    "occurrence": occ,
                    "mode": mode,
                    "fired": inj.fired,
                    "outcome": outcome,
                    "invariant_failures": inv_failures,
                }
            )
            failures.extend(
                f"{name}/{point}#{occ}/{mode}: {f}" for f in inv_failures
            )

    all_points = sorted({p for pts in discovered.values() for p in pts})
    novel = sorted(set(all_points) - KNOWN_DRILLED_POINTS)
    return {
        "ok": not failures,
        "seed": seed,
        "scenarios": names,
        "points": all_points,
        "num_points": len(all_points),
        "points_per_scenario": discovered,
        "novel_points": novel,
        "known_drilled": sorted(KNOWN_DRILLED_POINTS & set(all_points)),
        "num_injections": len(injections),
        "injections": injections,
        "schedule_sha256": schedule_sha256,
        "failures": failures,
    }
