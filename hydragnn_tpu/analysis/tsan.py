"""Runtime thread-sanitizer half of graftrace (docs/STATIC_ANALYSIS.md
"graftrace: the runtime half").

Opt-in (``HYDRAGNN_TSAN=1`` or :func:`enable`) instrumentation that wraps
the concurrency layer's REGISTERED locks and records, during fault drills
and tests:

* **actual lock-acquisition orders** — every ``A held while acquiring B``
  becomes a dynamic edge; an observed ``B -> A`` after ``A -> B`` is a
  dynamic lock-order inversion (the runtime witness of the static
  ``lock-order-inversion`` rule), recorded with both thread names;
* **cross-thread shared accesses** — code paths the static pass guards call
  :func:`shared_access` (inside their lock) with a site name; an access
  observed from >= 2 threads where some pair of observations shares NO
  common held lock is an *unregistered cross-thread access* (the runtime
  witness of ``unguarded-shared-write``);
* **seeded yield-point schedule fuzzing** — :func:`yield_point` sites
  perturb thread interleavings with tiny sleeps decided by a per-site
  deterministic PRNG stream (seed x site-name x visit-count), so a drill
  that exposes a race under seed S exposes it under seed S every time.

Zero cost when disabled: ``instrument_lock`` returns the lock unchanged and
``shared_access``/``yield_point`` return after one module-bool check — the
serve hot path stays uninstrumented unless an operator asks.

:func:`cross_check` merges the dynamic edges into the static lock-order
graph (analysis/concurrency.py ``TraceReport.lock_edges``) and reports any
cycle the union introduces: a dynamic order the static model missed, or a
static order production contradicts.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

_ENV_FLAG = "HYDRAGNN_TSAN"
_ENV_SEED = "HYDRAGNN_TSAN_SEED"

_enabled = os.environ.get(_ENV_FLAG, "") == "1"
_seed = int(os.environ.get(_ENV_SEED, "0") or 0)

_registry_lock = threading.Lock()
_held = threading.local()  # per-thread stack of held instrumented-lock names

# The registry is the one object the sanitizer itself must keep consistent —
# graftrace checks these declarations like any other module's (dogfood).
_edges: Dict[Tuple[str, str], Tuple[str, int]] = {}  # guarded-by: _registry_lock
_inversions: List[Dict[str, str]] = []  # guarded-by: _registry_lock
_accesses: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}  # guarded-by: _registry_lock
_unregistered: List[Dict[str, str]] = []  # guarded-by: _registry_lock
_yield_counts: Dict[str, int] = {}  # guarded-by: _registry_lock
_yield_schedule: Dict[str, List[int]] = {}  # guarded-by: _registry_lock


def enabled() -> bool:
    return _enabled


def enable(seed: int = 0) -> None:
    """Turn instrumentation on for locks created AFTER this call (tests and
    drills call this before constructing the engine/checkpointer)."""
    global _enabled, _seed
    _enabled = True
    _seed = int(seed)


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Clear every recorded fact (the enable flag and seed persist)."""
    with _registry_lock:
        _edges.clear()
        _inversions.clear()
        _accesses.clear()
        _unregistered.clear()
        _yield_counts.clear()
        _yield_schedule.clear()


def _held_stack() -> List[str]:
    stack = getattr(_held, "stack", None)
    if stack is None:
        stack = _held.stack = []
    return stack


class TsanLock:
    """Lock proxy recording acquisition order. Supports the ``with`` protocol
    plus acquire/release/locked, so it drops in for ``threading.Lock``."""

    __slots__ = ("_lock", "name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._on_acquire()
        return got

    def release(self) -> None:
        self._on_release()
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self._lock.acquire()
        self._on_acquire()
        return self

    def __exit__(self, *exc):
        self._on_release()
        self._lock.release()

    # ------------------------------------------------------------- recording
    def _on_acquire(self) -> None:
        stack = _held_stack()
        if stack:
            thread = threading.current_thread().name
            with _registry_lock:
                for h in stack:
                    if h == self.name:
                        continue
                    key = (h, self.name)
                    prev = _edges.get(key)
                    _edges[key] = (thread, (prev[1] if prev else 0) + 1)
                    rev = _edges.get((self.name, h))
                    if rev is not None:
                        _inversions.append(
                            {
                                "first": f"{h} -> {self.name}",
                                "first_thread": thread,
                                "second": f"{self.name} -> {h}",
                                "second_thread": rev[0],
                            }
                        )
        stack.append(self.name)

    def _on_release(self) -> None:
        stack = _held_stack()
        if self.name in stack:
            # Remove the most recent acquisition (non-LIFO release legal).
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] == self.name:
                    del stack[i]
                    break


def instrument_lock(lock, name: str):
    """Wrap ``lock`` for order recording when the sanitizer is enabled;
    return it unchanged (zero overhead) when not."""
    if not _enabled:
        return lock
    return TsanLock(lock, name)


def shared_access(site: str) -> None:
    """Record one access to a registered shared-state site from the current
    thread with the currently-held instrumented locks. Call INSIDE the
    guarding lock — a site observed from two threads with no common held
    lock is an unregistered cross-thread access."""
    if not _enabled:
        return
    thread = threading.current_thread().name
    locks = frozenset(_held_stack())
    with _registry_lock:
        seen = _accesses.setdefault(site, [])
        for other_thread, other_locks in seen:
            if other_thread != thread and not (locks & other_locks):
                _unregistered.append(
                    {
                        "site": site,
                        "thread_a": other_thread,
                        "locks_a": ",".join(sorted(other_locks)) or "<none>",
                        "thread_b": thread,
                        "locks_b": ",".join(sorted(locks)) or "<none>",
                    }
                )
                break
        # Bound the per-site memory: distinct (thread, locks) shapes only.
        if (thread, locks) not in seen:
            seen.append((thread, locks))


def yield_point(site: str) -> None:
    """Annotated interleaving site: under a seeded schedule, deterministically
    decide (per site visit) whether to yield the GIL / sleep briefly, so
    thread interleavings are perturbed the same way for the same seed."""
    if not _enabled:
        return
    # Visit allocation, decision, and schedule append are ONE critical
    # section: split in two, concurrent visitors could append out of visit
    # order and the recorded schedule would be interleaving-dependent —
    # the exact nondeterminism this module exists to remove.
    with _registry_lock:
        n = _yield_counts.get(site, 0)
        _yield_counts[site] = n + 1
        decision = _decide(site, n)
        _yield_schedule.setdefault(site, []).append(decision)
    if decision == 1:
        time.sleep(0)  # release the GIL, stay on the runqueue
    elif decision == 2:
        time.sleep(0.0005)  # force a reschedule window


def _decide(site: str, visit: int) -> int:
    """Deterministic per-(seed, site, visit) decision in {0, 1, 2} — a hash
    stream, so a site's schedule never depends on OTHER threads' progress
    (the property that makes a seeded repro a repro)."""
    h = hashlib.sha256(f"{_seed}:{site}:{visit}".encode()).digest()
    return h[0] % 3


def schedule(site: Optional[str] = None):
    """The recorded yield decisions (per site, in visit order) — the
    determinism witness tests compare across runs."""
    with _registry_lock:
        if site is not None:
            return list(_yield_schedule.get(site, []))
        return {k: list(v) for k, v in _yield_schedule.items()}


def report() -> Dict:
    """Everything recorded since the last reset, JSON-shaped."""
    with _registry_lock:
        return {
            "enabled": _enabled,
            "seed": _seed,
            "lock_edges": sorted(
                f"{a} -> {b}" for (a, b) in _edges
            ),
            "dynamic_inversions": list(_inversions),
            "shared_sites": {
                site: sorted({t for t, _ in obs})
                for site, obs in _accesses.items()
            },
            "unregistered_cross_thread": list(_unregistered),
            "yield_counts": dict(_yield_counts),
        }


def dynamic_edges() -> List[Tuple[str, str]]:
    with _registry_lock:
        return sorted(_edges)


def cross_check(static_edges: Sequence[Tuple[str, str]]) -> Dict:
    """Merge the dynamic acquisition orders into the static lock-order graph
    and look for cycles in the union. ``static_edges`` come from
    ``TraceReport.lock_edges`` — lock ids there are ``path::Class.attr``;
    dynamic names are the ``instrument_lock`` registration names
    (``Class.attr``), so both sides are compared on their ``Class.attr``
    tails."""

    def tail(lock: str) -> str:
        return lock.split("::")[-1]

    graph: Dict[str, Set[str]] = {}
    for a, b in static_edges:
        graph.setdefault(tail(a), set()).add(tail(b))
        graph.setdefault(tail(b), set())
    for a, b in dynamic_edges():
        graph.setdefault(tail(a), set()).add(tail(b))
        graph.setdefault(tail(b), set())

    cycles: List[List[str]] = []
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(node: str) -> None:
        color[node] = 1
        stack.append(node)
        for succ in sorted(graph.get(node, ())):
            if color.get(succ, 0) == 0:
                dfs(succ)
            elif color.get(succ) == 1:
                cycles.append(stack[stack.index(succ):] + [succ])
        stack.pop()
        color[node] = 2

    for node in sorted(graph):
        if color.get(node, 0) == 0:
            dfs(node)
    with _registry_lock:
        dynamic_findings = bool(_inversions or _unregistered)
    return {
        "static_edges": len(static_edges),
        "dynamic_edges": len(dynamic_edges()),
        "merged_cycles": cycles,
        "ok": not cycles and not dynamic_findings,
    }
