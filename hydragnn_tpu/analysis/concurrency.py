"""graftrace — static lock-discipline + thread-topology analyzer for the
host concurrency layer (rule catalogue: rules.py, policy + examples:
docs/STATIC_ANALYSIS.md "graftrace").

graftlint deliberately analyzes code reachable from compiled step bodies;
this pass covers its blind spot: the five cooperating host-side thread
roots — the ``_Prefetcher``/``DeviceFeed`` pipeline threads, the serve
engine's batcher + dispatcher + HTTP handler threads, the checkpoint
writer daemon, and the supervisor loop — and the shared state they touch
(metrics counters, executable caches, manifests, queues).

Three passes over the same parsed-module/callgraph infrastructure the
linter owns (Tracer subclasses graftlint.Linter):

1. **Thread topology.** Thread roots are discovered statically —
   ``threading.Thread(target=...)`` (root named by the ``name=`` literal),
   ``BaseHTTPRequestHandler`` subclasses (per-connection handler threads),
   and the framework's higher-order bindings (``DeviceFeed(iterable,
   transfer=...)`` runs its arguments on the feed-host / feed-transfer
   threads — rules.THREAD_CALLABLE_BINDINGS, the runs-on analog of
   TRACED_FACTORIES). Every other function starts on ``main``; the
   runs-on-thread set is propagated over the static call graph (direct
   calls, ``self.`` methods, ``Class.method`` refs, ``self.attr.method``
   through inferred attribute types, and cross-module imports) to a
   fixpoint, exactly the way rules.py propagates tracedness.

2. **Shared-state inventory + lock discipline.** Attribute writes are
   inventoried per ``(module, class, attr)``; ``__init__`` writes are
   pre-publication and exempt. An attribute written from >= 2 thread roots
   must carry a ``# guarded-by:`` declaration (grammar below), and every
   declared attribute's access sites must be statically enclosed in a
   ``with <declared lock>:``. A dynamic ``setattr(self, name, ...)`` with a
   non-literal name is conservatively a write to EVERY attribute of the
   class. Rules: ``missing-guard-decl``, ``unguarded-shared-write`` (never
   baselineable), ``guard-mismatch``.

3. **Lock-order graph + hazards.** ``with`` nesting (including through
   calls, via each function's transitive may-acquire set) yields a static
   lock-order graph; cycles are ``lock-order-inversion``. Unbounded
   blocking ops (queue get/put/join, Event.wait, Thread.join — typed from
   ``__init__`` construction) while holding a lock are
   ``blocking-queue-in-lock``; ``os.fork``/fork-context multiprocessing in
   this thread-spawning package is ``fork-after-threads``; JAX dispatch
   from a non-sanctioned root is ``jax-dispatch-off-main``.

``guarded-by`` declaration grammar (comment on the attribute's assignment
line or the line above)::

    self.requests_total = 0          # guarded-by: self._lock
    self.latency = {...}             # guarded-by: self._lock, dirty-reads(immutable after construction)
    self._result = None              # guarded-by: none(at-most-once overwrite; Event.set is the barrier)
    self.graphs = {}                 # guarded-by: external(callers hold their own lock)

``none``/``external`` REQUIRE the parenthesized reason — an unexplained
lock-free field is a prose invariant again. ``dirty-reads(<reason>)``
exempts read sites only; writes always need the lock.

Known under-approximations (documented, deliberate): objects that escape
through opaque iterators (a loader consumed by the feed's host thread) keep
their statically-visible roots; reads are checked for ``self.X``/``cls.X``/
``Class.X`` forms, not through arbitrary object references. Both err toward
silence, never toward false alarms — the suppression budget stays honest.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import rules as R
from .graftlint import (
    _FUNC_NODES,
    FuncInfo,
    Linter,
    ModuleInfo,
    Report,
    Violation,
    _dotted,
    _own_walk,
)

_GUARD_RE = re.compile(
    r"#\s*guarded-by:\s*"
    r"(?P<lock>none(?![\w.])|external(?![\w.])|[A-Za-z_][\w.]*)"
    r"\s*(?:\((?P<reason>[^)]*)\))?"
    r"\s*(?:,\s*dirty-reads\s*\((?P<dirty>[^)]*)\))?"
)


@dataclass
class GuardDecl:
    lock: str  # canonical lock id, or "none" / "external"
    line: int
    reason: Optional[str] = None  # required for none/external
    dirty_reads: Optional[str] = None  # reason unlocked reads are safe
    # True when the comment is the whole line: only a standalone comment may
    # declare for the assignment BELOW it — a trailing comment always binds
    # to its own line's attribute, never the next one's.
    standalone: bool = True


@dataclass
class AttrInfo:
    """One shared-state candidate: an attribute of a class (or a module
    global mutated from functions)."""

    key: Tuple[str, str, str]  # (relpath, class or "<module>", attr)
    ctor_type: Optional[str] = None  # canonical constructor, if inferable
    self_sync: bool = False  # rules.THREAD_SAFE_TYPES construction
    is_lock: bool = False
    decl: Optional[GuardDecl] = None
    writes: List[Tuple[FuncInfo, ast.AST, bool]] = field(default_factory=list)
    # (fn, node, in_init); reads exclude __init__ sites
    reads: List[Tuple[FuncInfo, ast.AST]] = field(default_factory=list)

    @property
    def write_roots(self) -> Set[str]:
        roots: Set[str] = set()
        for fn, _node, in_init in self.writes:
            if not in_init:
                roots |= fn.roots
        return roots


@dataclass
class TraceReport(Report):
    """graftrace run result: graftlint's Report plus the topology/lock-graph
    facts the runtime half (tsan.py) cross-checks."""

    thread_roots: Dict[str, List[str]] = field(default_factory=dict)
    shared_attrs: List[str] = field(default_factory=list)
    declared_attrs: int = 0
    lock_nodes: List[str] = field(default_factory=list)
    lock_edges: List[Tuple[str, str]] = field(default_factory=list)
    lock_cycles: List[List[str]] = field(default_factory=list)


_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition")


def _is_constant_name(attr: str) -> bool:
    """ALL_CAPS attributes are class constants by convention — assigned once
    at class-definition time, immutable thereafter; the dynamic-setattr taint
    must not demand guard declarations for them."""
    bare = attr.lstrip("_")
    return bool(bare) and bare == bare.upper() and any(c.isalpha() for c in bare)


class Tracer(Linter):
    """The graftrace pass. Reuses the linter's parsing, import resolution,
    and suppression machinery; adds thread roots, attribute inventory, and
    the lock graph."""

    def __init__(self, paths: Sequence[str], root: Optional[str] = None):
        super().__init__(paths, root=root)
        # (relpath, ClassName) -> ModuleInfo (class definition site)
        self.classes: Dict[Tuple[str, str], ModuleInfo] = {}
        # class name -> [(mod, name)] for simple-name resolution
        self._class_sites: Dict[str, List[Tuple[ModuleInfo, str]]] = {}
        # (mod.relpath, cls, attr) -> (def_mod.relpath, def_cls) attr type
        self.attr_types: Dict[Tuple[str, str, str], Tuple[str, str]] = {}
        self.attrs: Dict[Tuple[str, str, str], AttrInfo] = {}
        self.guard_decls: Dict[str, Dict[int, GuardDecl]] = {}
        # lock graph: canonical lock id -> {successor: (mod, node, fn)}
        self.lock_graph: Dict[str, Dict[str, Tuple[ModuleInfo, ast.AST, str]]] = {}
        self._fn_acquires: Dict[int, Set[str]] = {}  # id(fn) -> lock ids
        self._fn_blocks: Dict[int, Optional[str]] = {}  # id(fn) -> blocking-op desc
        self.http_handler_classes: Set[Tuple[str, str]] = set()
        self.roots_found: Dict[str, List[str]] = {}

    # ------------------------------------------------------------------ run
    def run(self, check_suppressions: bool = True) -> TraceReport:  # type: ignore[override]
        report = TraceReport()
        self.load(report)
        self._index_classes()
        self._collect_guard_comments()
        self._infer_attr_types()
        self._discover_roots()
        self._propagate_roots()
        self._inventory_attrs()
        self._check_guards(report)
        self._build_lock_graph(report)
        self._check_lock_cycles(report)
        self._check_blocking_and_forks(report)
        self._check_jax_dispatch(report)
        if check_suppressions:
            self._check_bare_suppressions(report)
        report.thread_roots = {
            k: sorted(v) for k, v in sorted(self.roots_found.items())
        }
        report.shared_attrs = sorted(
            "::".join(a.key)
            for a in self.attrs.values()
            if len(a.write_roots) >= 2
        )
        report.declared_attrs = sum(
            1 for a in self.attrs.values() if a.decl is not None
        )
        report.lock_nodes = sorted(self.lock_graph)
        report.lock_edges = sorted(
            (a, b) for a, succ in self.lock_graph.items() for b in succ
        )
        report.violations.sort(key=lambda v: (v.path, v.line, v.col))
        report.suppressed.sort(key=lambda v: (v.path, v.line, v.col))
        return report

    # ------------------------------------------------------------- indexing
    def _index_classes(self) -> None:
        for mod in self.modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes[(mod.relpath, node.name)] = mod
                    self._class_sites.setdefault(node.name, []).append(
                        (mod, node.name)
                    )
                    for base in node.bases:
                        tail = (_dotted(base) or "").split(".")[-1]
                        if tail in R.HTTP_HANDLER_BASES:
                            self.http_handler_classes.add(
                                (mod.relpath, node.name)
                            )

    def _resolve_class(
        self, mod: ModuleInfo, name: str
    ) -> Optional[Tuple[ModuleInfo, str]]:
        """A simple class name in ``mod``'s scope -> its defining module."""
        if (mod.relpath, name) in self.classes:
            return mod, name
        imp = mod.from_imports.get(name)
        if imp:
            src = self.by_dotted.get(imp[0])
            if src and (src.relpath, imp[1]) in self.classes:
                return src, imp[1]
        return None

    def _collect_guard_comments(self) -> None:
        for mod in self.modules:
            decls: Dict[int, GuardDecl] = {}
            try:
                toks = tokenize.generate_tokens(
                    io.StringIO(mod.source).readline
                )
                for tok in toks:
                    if tok.type != tokenize.COMMENT:
                        continue
                    m = _GUARD_RE.search(tok.string)
                    if not m:
                        continue
                    reason = m.group("reason")
                    dirty = m.group("dirty")
                    decls[tok.start[0]] = GuardDecl(
                        lock=m.group("lock"),
                        line=tok.start[0],
                        reason=reason.strip() if reason else None,
                        dirty_reads=dirty.strip() if dirty else None,
                        standalone=not tok.line[: tok.start[1]].strip(),
                    )
            except tokenize.TokenError:
                pass
            self.guard_decls[mod.relpath] = decls

    # -------------------------------------------------------- type inference
    def _infer_attr_types(self) -> None:
        """``self.X = ServeMetrics()`` / ``self.X = <annotated param>`` ->
        (defining module, class) for ``self.X.method`` resolution and for
        thread-safe/lock typing."""
        for mod in self.modules:
            for fn in mod.functions:
                cls = self._enclosing_class(fn)
                if cls is None:
                    continue
                ann = self._param_annotations(mod, fn)
                for node in _own_walk(fn.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    for t in node.targets:
                        d = _dotted(t)
                        if not d or "." not in d:
                            continue
                        head, _, attr = d.partition(".")
                        if head not in ("self", "cls") or "." in attr:
                            continue
                        key = (mod.relpath, cls, attr)
                        typed = self._expr_class(mod, node.value, ann)
                        if typed and key not in self.attr_types:
                            self.attr_types[key] = (
                                typed[0].relpath,
                                typed[1],
                            )

    def _param_annotations(
        self, mod: ModuleInfo, fn: FuncInfo
    ) -> Dict[str, Tuple[ModuleInfo, str]]:
        out: Dict[str, Tuple[ModuleInfo, str]] = {}
        args = getattr(fn.node, "args", None)
        if args is None:
            return out
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is None:
                continue
            t = self._annotation_class(mod, a.annotation)
            if t:
                out[a.arg] = t
        return out

    def _annotation_class(
        self, mod: ModuleInfo, node: ast.AST
    ) -> Optional[Tuple[ModuleInfo, str]]:
        if isinstance(node, ast.Subscript):  # Optional[X] / "X" | None
            return self._annotation_class(mod, node.slice)
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            name = node.value.split(".")[-1].strip("'\" ")
            return self._resolve_class(mod, name)
        d = _dotted(node)
        if d:
            return self._resolve_class(mod, d.split(".")[-1])
        return None

    def _expr_class(
        self,
        mod: ModuleInfo,
        expr: ast.AST,
        ann: Dict[str, Tuple[ModuleInfo, str]],
    ) -> Optional[Tuple[ModuleInfo, str]]:
        """First analyzed-class constructor call (or annotated-param name)
        found anywhere in the RHS expression."""
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d:
                    resolved = self._resolve_class(mod, d.split(".")[-1])
                    if resolved:
                        return resolved
            elif isinstance(node, ast.Name) and node.id in ann:
                return ann[node.id]
        return None

    @staticmethod
    def _enclosing_class(fn: FuncInfo) -> Optional[str]:
        cur: Optional[FuncInfo] = fn
        while cur is not None:
            if cur.class_name:
                return cur.class_name
            cur = cur.parent
        return None

    # --------------------------------------------------------- thread roots
    def _add_root(self, root: str, fn: Optional[FuncInfo], where: str) -> None:
        self.roots_found.setdefault(root, [])
        if fn is not None:
            fn.roots.add(root)
            self.roots_found[root].append(f"{where}::{fn.qualname}")
        else:
            self.roots_found[root].append(f"{where}::<external>")

    def _resolve_callable_arg(
        self, mod: ModuleInfo, fn: FuncInfo, arg: ast.AST
    ) -> Optional[FuncInfo]:
        """The function a callable/generator argument executes: a name, a
        ``self.method`` ref, a lambda, a generator call ``self.gen(...)``,
        or ``map(f, ...)``'s first argument."""
        if isinstance(arg, ast.Lambda):
            return mod.func_by_node.get(arg)
        if isinstance(arg, ast.Call):
            callee = _dotted(arg.func)
            if callee == "map" and arg.args:
                return self._resolve_callable_arg(mod, fn, arg.args[0])
            if callee:
                return self._resolve_call_ext(mod, fn, callee)
            return None
        d = _dotted(arg)
        if d:
            return self._resolve_call_ext(mod, fn, d)
        return None

    def _discover_roots(self) -> None:
        for mod in self.modules:
            # HTTP handler classes: every method runs on a connection thread.
            for fn in mod.functions:
                if (
                    fn.class_name
                    and (mod.relpath, fn.class_name)
                    in self.http_handler_classes
                ):
                    self._add_root(R.HTTP_HANDLER_ROOT, fn, mod.relpath)
                # Nested defs of the declared thread factories.
                p = fn.parent
                while p is not None:
                    if p.name in R.THREAD_FACTORY_ROOTS:
                        self._add_root(
                            R.THREAD_FACTORY_ROOTS[p.name], fn, mod.relpath
                        )
                        break
                    p = p.parent
            for fn in mod.functions:
                for dotted, call in fn.calls:
                    tail = dotted.split(".")[-1]
                    canon = mod.canonical(dotted) or ""
                    if (
                        tail == "Thread"
                        or canon in ("threading.Thread",)
                        or canon.endswith(".threading.Thread")
                    ):
                        target = None
                        name = None
                        for kw in call.keywords:
                            if kw.arg == "target":
                                target = kw.value
                            elif kw.arg == "name" and isinstance(
                                kw.value, ast.Constant
                            ):
                                name = str(kw.value.value)
                        if target is None:
                            continue
                        tfn = self._resolve_callable_arg(mod, fn, target)
                        root = name or (
                            tfn.qualname if tfn else (_dotted(target) or "?")
                        )
                        self._add_root(root, tfn, mod.relpath)
                    elif tail in R.THREAD_CALLABLE_BINDINGS:
                        binding = R.THREAD_CALLABLE_BINDINGS[tail]
                        for i, arg in enumerate(call.args):
                            if i in binding:
                                tfn = self._resolve_callable_arg(mod, fn, arg)
                                if tfn is not None:
                                    self._add_root(
                                        binding[i], tfn, mod.relpath
                                    )
                        for kw in call.keywords:
                            if kw.arg in binding:
                                tfn = self._resolve_callable_arg(
                                    mod, fn, kw.value
                                )
                                if tfn is not None:
                                    self._add_root(
                                        binding[kw.arg], tfn, mod.relpath
                                    )

    def _resolve_call_ext(
        self, mod: ModuleInfo, fn: FuncInfo, dotted: str
    ) -> Optional[FuncInfo]:
        """Linter resolution + Class.method, self.attr.method (typed), and
        constructor-to-__init__ edges."""
        base = self._resolve_call(mod, fn, dotted)
        if base is not None:
            return base
        parts = dotted.split(".")
        if len(parts) == 1:
            resolved = self._resolve_class(mod, parts[0])
            if resolved:
                dmod, cname = resolved
                return dmod.methods.get((cname, "__init__"))
            return None
        if len(parts) == 2:
            resolved = self._resolve_class(mod, parts[0])
            if resolved:
                dmod, cname = resolved
                return dmod.methods.get((cname, parts[1]))
        if len(parts) == 3 and parts[0] in ("self", "cls"):
            cls = self._enclosing_class(fn)
            if cls:
                t = self.attr_types.get((mod.relpath, cls, parts[1]))
                if t:
                    dmod = next(
                        (m for m in self.modules if m.relpath == t[0]), None
                    )
                    if dmod:
                        return dmod.methods.get((t[1], parts[2]))
        return None

    def _propagate_roots(self) -> None:
        # Everything not exclusively a thread body starts on main.
        for mod in self.modules:
            for fn in mod.functions:
                if not fn.roots:
                    fn.roots.add(R.MAIN_THREAD_ROOT)
        changed = True
        while changed:
            changed = False
            for mod in self.modules:
                for fn in mod.functions:
                    for dotted, _ in fn.calls:
                        target = self._resolve_call_ext(mod, fn, dotted)
                        if target is None:
                            continue
                        if not fn.roots <= target.roots:
                            target.roots |= fn.roots
                            changed = True

    # -------------------------------------------------- attribute inventory
    def _attr_of_target(
        self, mod: ModuleInfo, fn: FuncInfo, node: ast.AST
    ) -> Optional[Tuple[str, str, str]]:
        """(relpath, class-or-<module>, attr) for self.X / cls.X / Class.X
        targets, and module globals (Name known at module level)."""
        d = _dotted(node)
        if not d:
            return None
        parts = d.split(".")
        if len(parts) == 2:
            if parts[0] in ("self", "cls"):
                cls = self._enclosing_class(fn)
                if cls:
                    return (mod.relpath, cls, parts[1])
                return None
            resolved = self._resolve_class(mod, parts[0])
            if resolved:
                dmod, cname = resolved
                return (dmod.relpath, cname, parts[1])
            return None
        if len(parts) == 1:
            if self._is_module_global(mod, fn, parts[0]):
                return (mod.relpath, "<module>", parts[0])
        return None

    def _is_module_global(
        self, mod: ModuleInfo, fn: FuncInfo, name: str
    ) -> bool:
        globals_ = self._module_globals(mod)
        if name not in globals_:
            return False
        # Shadowed by a parameter or a local plain assignment?
        args = getattr(fn.node, "args", None)
        if args is not None:
            names = {a.arg for a in args.args + args.kwonlyargs}
            if args.vararg:
                names.add(args.vararg.arg)
            if args.kwarg:
                names.add(args.kwarg.arg)
            if name in names:
                return False
        for node in _own_walk(fn.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return False
        return True

    def _module_globals(self, mod: ModuleInfo) -> Set[str]:
        cached = getattr(mod, "_trace_globals", None)
        if cached is not None:
            return cached
        out: Set[str] = set()
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                stmt.target, ast.Name
            ):
                out.add(stmt.target.id)
        mod._trace_globals = out  # type: ignore[attr-defined]
        return out

    def _attr_info(self, key: Tuple[str, str, str]) -> AttrInfo:
        info = self.attrs.get(key)
        if info is None:
            info = self.attrs[key] = AttrInfo(key=key)
        return info

    def _is_init(self, fn: FuncInfo, key: Tuple[str, str, str]) -> bool:
        """Pre-publication writes: inside the owning class's __init__ (or
        functions nested in it)."""
        cur: Optional[FuncInfo] = fn
        while cur is not None:
            if cur.name == "__init__" and self._enclosing_class(cur) == key[1]:
                return True
            cur = cur.parent
        return False

    def _note_assignment(
        self,
        mod: ModuleInfo,
        fn: Optional[FuncInfo],
        key: Tuple[str, str, str],
        node: ast.AST,
        value: Optional[ast.AST],
    ) -> None:
        info = self._attr_info(key)
        line = getattr(node, "lineno", 0)
        decls = self.guard_decls.get(mod.relpath, {})
        for probe in (line, line - 1):
            d = decls.get(probe)
            if d and probe == line - 1 and not d.standalone:
                d = None  # a trailing comment binds to ITS line's attribute
            if d and info.decl is None:
                info.decl = GuardDecl(
                    lock=self._canonical_decl_lock(mod, key, d.lock),
                    line=d.line,
                    reason=d.reason,
                    dirty_reads=d.dirty_reads,
                )
        if value is not None and info.ctor_type is None:
            for sub in ast.walk(value):
                if isinstance(sub, ast.Call):
                    canon = mod.canonical(_dotted(sub.func)) or ""
                    tail2 = ".".join(canon.split(".")[-2:])
                    for probe_t in (canon, tail2):
                        if (
                            probe_t in R.THREAD_SAFE_TYPES
                            or probe_t in R.BLOCKING_METHODS_BY_TYPE
                            or probe_t in _LOCK_CTORS
                        ):
                            info.ctor_type = probe_t
                            info.self_sync = probe_t in R.THREAD_SAFE_TYPES
                            info.is_lock = probe_t in _LOCK_CTORS
                            break
                    if info.ctor_type:
                        break
        in_init = fn is None or self._is_init(fn, key)
        if fn is not None:
            info.writes.append((fn, node, in_init))

    def _canonical_decl_lock(
        self, mod: ModuleInfo, key: Tuple[str, str, str], lock: str
    ) -> str:
        if lock in ("none", "external"):
            return lock
        return self._canonical_lock(mod, key[1], lock)

    def _canonical_lock(
        self, mod: ModuleInfo, cls: Optional[str], expr: str
    ) -> str:
        """Canonical lock id for a dotted lock expression in (mod, class)
        context: ``self._lock``/``cls._lock`` -> ``mod::Class._lock``;
        ``Other._lock`` resolves through imports; bare names are module
        globals."""
        parts = expr.split(".")
        if len(parts) == 2 and parts[0] in ("self", "cls") and cls:
            return f"{mod.relpath}::{cls}.{parts[1]}"
        if len(parts) == 2:
            resolved = self._resolve_class(mod, parts[0])
            if resolved:
                dmod, cname = resolved
                return f"{dmod.relpath}::{cname}.{parts[1]}"
        if len(parts) == 3 and parts[0] in ("self", "cls") and cls:
            t = self.attr_types.get((mod.relpath, cls, parts[1]))
            if t:
                return f"{t[0]}::{t[1]}.{parts[2]}"
        if len(parts) == 1:
            return f"{mod.relpath}::{expr}"
        return f"{mod.relpath}::<expr>{expr}"

    def _inventory_attrs(self) -> None:
        for mod in self.modules:
            # Class-body assignments (class attrs, incl. their decls/types).
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    tgt = None
                    val = None
                    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                        tgt, val = stmt.targets[0], stmt.value
                    elif isinstance(stmt, ast.AnnAssign):
                        tgt, val = stmt.target, stmt.value
                    if isinstance(tgt, ast.Name):
                        self._note_assignment(
                            mod,
                            None,
                            (mod.relpath, node.name, tgt.id),
                            stmt,
                            val,
                        )
            # Module-level globals (decl + ctor typing).
            for stmt in mod.tree.body:
                tgt = None
                val = None
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    tgt, val = stmt.targets[0], stmt.value
                elif isinstance(stmt, ast.AnnAssign):
                    tgt, val = stmt.target, stmt.value
                if isinstance(tgt, ast.Name):
                    self._note_assignment(
                        mod,
                        None,
                        (mod.relpath, "<module>", tgt.id),
                        stmt,
                        val,
                    )
            # Function-body writes and reads.
            for fn in mod.functions:
                self._inventory_fn(mod, fn)

    def _inventory_fn(self, mod: ModuleInfo, fn: FuncInfo) -> None:
        for node in _own_walk(fn.node):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                value = getattr(node, "value", None)
                for t in targets:
                    base = t
                    if isinstance(base, ast.Subscript):
                        base = base.value  # container-element write
                    if isinstance(base, (ast.Tuple, ast.List)):
                        for elt in base.elts:
                            key = self._attr_of_target(mod, fn, elt)
                            if key:
                                self._note_assignment(
                                    mod, fn, key, node, value
                                )
                        continue
                    key = self._attr_of_target(mod, fn, base)
                    if key:
                        self._note_assignment(mod, fn, key, node, value)
            elif isinstance(node, ast.Call):
                self._inventory_call(mod, fn, node)
            elif isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                key = self._attr_of_target(mod, fn, node)
                if key and not self._is_init(fn, key):
                    self._attr_info(key).reads.append((fn, node))

    def _inventory_call(
        self, mod: ModuleInfo, fn: FuncInfo, node: ast.Call
    ) -> None:
        # Container mutators: self.X.append(...) is a write to X.
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in R.MUTATOR_METHODS
        ):
            key = self._attr_of_target(mod, fn, node.func.value)
            if key:
                info = self._attr_info(key)
                if not info.self_sync:
                    self._note_assignment(mod, fn, key, node, None)
        # Dynamic setattr: non-literal name taints every attr of the class.
        callee = _dotted(node.func)
        if callee == "setattr" and len(node.args) >= 2:
            obj, name_arg = node.args[0], node.args[1]
            target_cls: Optional[Tuple[str, str]] = None
            d = _dotted(obj)
            if d in ("self", "cls"):
                cls = self._enclosing_class(fn)
                if cls:
                    target_cls = (mod.relpath, cls)
            elif d and d.startswith("self.") and d.count(".") == 1:
                cls = self._enclosing_class(fn)
                t = (
                    self.attr_types.get((mod.relpath, cls, d.split(".")[1]))
                    if cls
                    else None
                )
                if t:
                    target_cls = t
            if target_cls is None:
                return
            if isinstance(name_arg, ast.Constant) and isinstance(
                name_arg.value, str
            ):
                self._note_assignment(
                    mod,
                    fn,
                    (target_cls[0], target_cls[1], name_arg.value),
                    node,
                    None,
                )
            else:
                for key, info in list(self.attrs.items()):
                    if (
                        key[0] == target_cls[0]
                        and key[1] == target_cls[1]
                        and not info.self_sync
                        and not info.is_lock
                        and not _is_constant_name(key[2])
                    ):
                        info.writes.append((fn, node, False))

    # ------------------------------------------------------ guard discipline
    def _held_locks_map(
        self, mod: ModuleInfo, fn: FuncInfo
    ) -> Dict[int, frozenset]:
        """id(node) -> frozenset of canonical lock ids held at that node
        (intra-procedural ``with`` nesting)."""
        cached = getattr(fn, "_trace_held", None)
        if cached is not None:
            return cached
        held_map: Dict[int, frozenset] = {}
        cls = self._enclosing_class(fn)

        def lock_ids(item: ast.withitem) -> Optional[str]:
            d = _dotted(item.context_expr)
            if not d:
                return None
            lock_id = self._canonical_lock(mod, cls, d)
            info = self.attrs.get(self._lock_attr_key(lock_id))
            if info is not None and info.is_lock:
                return lock_id
            # Unknown object: treat names/attrs containing "lock" as locks
            # (fixture files declare locks the checker has not typed).
            if "lock" in d.split(".")[-1].lower():
                return lock_id
            return None

        def annotate(node: ast.AST, held: frozenset) -> None:
            if isinstance(node, _FUNC_NODES) and node is not fn.node:
                return  # nested defs hold nothing from the enclosing scope
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held_map[id(node)] = held
                new = held
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        held_map[id(sub)] = new
                    lid = lock_ids(item)
                    if lid is not None:
                        for h in new:
                            self._add_lock_edge(h, lid, mod, node, fn)
                        new = new | {lid}
                        self._fn_acquires.setdefault(id(fn), set()).add(lid)
                    if item.optional_vars is not None:
                        for sub in ast.walk(item.optional_vars):
                            held_map[id(sub)] = new
                for child in node.body:
                    annotate(child, new)
                return
            held_map[id(node)] = held
            for child in ast.iter_child_nodes(node):
                annotate(child, held)

        annotate(fn.node, frozenset())
        fn._trace_held = held_map  # type: ignore[attr-defined]
        return held_map

    @staticmethod
    def _lock_attr_key(lock_id: str) -> Tuple[str, str, str]:
        relpath, _, rest = lock_id.partition("::")
        if "." in rest:
            cls, _, attr = rest.partition(".")
            return (relpath, cls, attr)
        return (relpath, "<module>", rest)

    def _add_lock_edge(
        self, a: str, b: str, mod: ModuleInfo, node: ast.AST, fn: FuncInfo
    ) -> None:
        if a == b:
            return
        self.lock_graph.setdefault(a, {})
        self.lock_graph.setdefault(b, {})
        self.lock_graph[a].setdefault(b, (mod, node, fn.qualname))

    def _check_guards(self, report: TraceReport) -> None:
        mods_by_rel = {m.relpath: m for m in self.modules}
        for key, info in sorted(self.attrs.items()):
            if info.self_sync or info.is_lock:
                continue
            shared_roots = info.write_roots
            decl = info.decl
            if decl is None:
                if len(shared_roots) >= 2:
                    fn, node, _ = next(
                        (w for w in info.writes if not w[2]), info.writes[0]
                    )
                    self._emit(
                        report,
                        fn.module,
                        "missing-guard-decl",
                        node,
                        f"attribute {key[1]}.{key[2]} is written from "
                        f"thread roots {sorted(shared_roots)} but carries "
                        "no '# guarded-by:' declaration",
                        fn.qualname,
                    )
                continue
            if decl.lock in ("none", "external"):
                if not decl.reason:
                    mod = mods_by_rel.get(key[0])
                    if mod is not None:
                        report.violations.append(
                            Violation(
                                rule="missing-guard-decl",
                                path=key[0],
                                line=decl.line,
                                col=0,
                                message=(
                                    f"guarded-by: {decl.lock} on "
                                    f"{key[1]}.{key[2]} requires a reason: "
                                    f"# guarded-by: {decl.lock}(why this "
                                    "is safe)"
                                ),
                                qualname=f"{key[1]}.{key[2]}",
                            )
                        )
                continue
            # Declared lock: every non-init write must hold it; reads too
            # unless the declaration carries dirty-reads.
            for fn, node, in_init in info.writes:
                if in_init:
                    continue
                held = self._held_locks_map(fn.module, fn).get(
                    id(node), frozenset()
                )
                if decl.lock in held:
                    continue
                if held:
                    self._emit(
                        report,
                        fn.module,
                        "guard-mismatch",
                        node,
                        f"write to {key[1]}.{key[2]} holds "
                        f"{sorted(held)} but the declaration names "
                        f"{decl.lock}",
                        fn.qualname,
                    )
                else:
                    self._emit(
                        report,
                        fn.module,
                        "unguarded-shared-write",
                        node,
                        f"write to {key[1]}.{key[2]} outside "
                        f"'with {decl.lock.split('::')[-1]}:' "
                        f"(declared at {key[0]}:{decl.line})",
                        fn.qualname,
                    )
            if decl.dirty_reads:
                continue
            for fn, node in info.reads:
                held = self._held_locks_map(fn.module, fn).get(
                    id(node), frozenset()
                )
                if decl.lock not in held:
                    self._emit(
                        report,
                        fn.module,
                        "guard-mismatch",
                        node,
                        f"unlocked read of {key[1]}.{key[2]} (guarded-by "
                        f"{decl.lock.split('::')[-1]}; add a "
                        "dirty-reads(<reason>) clause if stale reads are "
                        "safe)",
                        fn.qualname,
                    )

    # --------------------------------------------------------- lock ordering
    def _build_lock_graph(self, report: TraceReport) -> None:
        # Direct with-nesting edges were recorded by _held_locks_map; force
        # the map for every function, then add cross-function edges from the
        # transitive may-acquire sets.
        for mod in self.modules:
            for fn in mod.functions:
                self._held_locks_map(mod, fn)
        # Transitive acquires to a fixpoint.
        trans: Dict[int, Set[str]] = {
            id(fn): set(self._fn_acquires.get(id(fn), set()))
            for mod in self.modules
            for fn in mod.functions
        }
        fns = [
            (mod, fn) for mod in self.modules for fn in mod.functions
        ]
        changed = True
        while changed:
            changed = False
            for mod, fn in fns:
                acc = trans[id(fn)]
                for dotted, _ in fn.calls:
                    target = self._resolve_call_ext(mod, fn, dotted)
                    if target is not None and not trans[id(target)] <= acc:
                        acc |= trans[id(target)]
                        changed = True
        self._fn_trans_acquires = trans
        # Call sites under a held lock acquire everything the callee may.
        for mod, fn in fns:
            held_map = self._held_locks_map(mod, fn)
            for dotted, call in fn.calls:
                held = held_map.get(id(call), frozenset())
                if not held:
                    continue
                target = self._resolve_call_ext(mod, fn, dotted)
                if target is None:
                    continue
                for inner in trans[id(target)]:
                    for h in held:
                        self._add_lock_edge(h, inner, mod, call, fn)

    def _check_lock_cycles(self, report: TraceReport) -> None:
        color: Dict[str, int] = {}
        stack: List[str] = []
        cycles: List[List[str]] = []

        def dfs(node: str) -> None:
            color[node] = 1
            stack.append(node)
            for succ in sorted(self.lock_graph.get(node, ())):
                if color.get(succ, 0) == 0:
                    dfs(succ)
                elif color.get(succ) == 1:
                    cycle = stack[stack.index(succ):] + [succ]
                    cycles.append(cycle)
            stack.pop()
            color[node] = 2

        for node in sorted(self.lock_graph):
            if color.get(node, 0) == 0:
                dfs(node)
        seen: Set[frozenset] = set()
        for cycle in cycles:
            sig = frozenset(cycle)
            if sig in seen:
                continue
            seen.add(sig)
            report.lock_cycles.append(cycle)
            a, b = cycle[0], cycle[1]
            mod, node, qual = self.lock_graph[a][b]
            self._emit(
                report,
                mod,
                "lock-order-inversion",
                node,
                "lock-order cycle: "
                + " -> ".join(c.split("::")[-1] for c in cycle)
                + " (two threads can deadlock acquiring these in opposite "
                "orders)",
                qual,
            )

    # ------------------------------------------------------------- hazards
    def _blocking_call_desc(
        self, mod: ModuleInfo, fn: FuncInfo, node: ast.Call
    ) -> Optional[str]:
        if not isinstance(node.func, ast.Attribute):
            return None
        meth = node.func.attr
        key = self._attr_of_target(mod, fn, node.func.value)
        if key is None:
            return None
        info = self.attrs.get(key)
        if info is None or info.ctor_type is None:
            return None
        blocking = R.BLOCKING_METHODS_BY_TYPE.get(info.ctor_type, ())
        if meth not in blocking:
            return None
        # Bounded waits are allowed: any timeout/block=False argument.
        for kw in node.keywords:
            if kw.arg in ("timeout",):
                return None
            if kw.arg == "block" and isinstance(kw.value, ast.Constant):
                if kw.value.value is False:
                    return None
        if meth == "get" and len(node.args) >= 2:
            return None
        if meth == "put" and len(node.args) >= 3:
            return None
        if meth in ("join", "wait") and node.args:
            return None
        return f"{key[2]}.{meth}()"

    def _check_blocking_and_forks(self, report: TraceReport) -> None:
        fns = [(mod, fn) for mod in self.modules for fn in mod.functions]
        # Per-function: the first unconditionally-blocking op description.
        blocks: Dict[int, Optional[str]] = {}
        for mod, fn in fns:
            desc = None
            for node in _own_walk(fn.node):
                if isinstance(node, ast.Call):
                    desc = self._blocking_call_desc(mod, fn, node)
                    if desc:
                        break
            blocks[id(fn)] = desc
        # Transitive: a call to a may-block function blocks.
        trans: Dict[int, Optional[str]] = dict(blocks)
        changed = True
        while changed:
            changed = False
            for mod, fn in fns:
                if trans[id(fn)]:
                    continue
                for dotted, _ in fn.calls:
                    target = self._resolve_call_ext(mod, fn, dotted)
                    if target is not None and trans.get(id(target)):
                        trans[id(fn)] = (
                            f"{dotted}() -> {trans[id(target)]}"
                        )
                        changed = True
                        break
        package_spawns_threads = bool(self.roots_found)
        for mod, fn in fns:
            held_map = self._held_locks_map(mod, fn)
            for node in _own_walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                held = held_map.get(id(node), frozenset())
                if held:
                    desc = self._blocking_call_desc(mod, fn, node)
                    if desc is None:
                        d = _dotted(node.func)
                        if d:
                            target = self._resolve_call_ext(mod, fn, d)
                            if target is not None and trans.get(id(target)):
                                desc = f"{d}() -> {trans[id(target)]}"
                    if desc:
                        self._emit(
                            report,
                            mod,
                            "blocking-queue-in-lock",
                            node,
                            f"unbounded blocking op {desc} while holding "
                            f"{sorted(h.split('::')[-1] for h in held)}",
                            fn.qualname,
                        )
                canon = mod.canonical(_dotted(node.func)) or ""
                if canon in R.FORK_CALLS and package_spawns_threads:
                    self._emit(
                        report,
                        mod,
                        "fork-after-threads",
                        node,
                        f"{canon}() in a thread-spawning package — the "
                        "child inherits held locks and dead threads",
                        fn.qualname,
                    )
                elif canon in R.MP_PROCESS_CALLS and package_spawns_threads:
                    if not self._spawn_context_visible(mod, fn):
                        self._emit(
                            report,
                            mod,
                            "fork-after-threads",
                            node,
                            f"{canon} without an explicit "
                            "spawn/forkserver context in a thread-spawning "
                            "package",
                            fn.qualname,
                        )

    @staticmethod
    def _spawn_context_visible(mod: ModuleInfo, fn: FuncInfo) -> bool:
        for node in _own_walk(fn.node):
            if isinstance(node, ast.Call):
                d = _dotted(node.func) or ""
                if d.endswith("get_context") and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) and arg.value in (
                        "spawn",
                        "forkserver",
                    ):
                        return True
        return False

    def _check_jax_dispatch(self, report: TraceReport) -> None:
        for mod in self.modules:
            for fn in mod.functions:
                bad = fn.roots - R.SANCTIONED_DISPATCH_ROOTS
                if not bad:
                    continue
                for node in _own_walk(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    canon = mod.canonical(_dotted(node.func)) or ""
                    if canon in R.JAX_DISPATCH_CALLS or any(
                        canon.startswith(p) for p in R.JAX_DISPATCH_PREFIXES
                    ):
                        self._emit(
                            report,
                            mod,
                            "jax-dispatch-off-main",
                            node,
                            f"{canon} dispatches device work from thread "
                            f"root(s) {sorted(bad)} — only the DeviceFeed "
                            "transfer stage and the serve dispatcher may "
                            "touch the device off-main",
                            fn.qualname,
                        )

    # ------------------------------------------------------ suppression meta
    def _check_bare_suppressions(self, report: TraceReport) -> None:
        """Reason-less / unknown-rule suppressions for the CONCURRENCY rules
        only (the lint pass owns the check for its own rules; the combined
        CLI run disables this half to avoid double reports)."""
        for mod in self.modules:
            for line, (rule, reason) in sorted(mod.suppressions.items()):
                if rule not in R.CONCURRENCY_RULES:
                    continue
                if not reason:
                    report.violations.append(
                        Violation(
                            rule="suppression-without-reason",
                            path=mod.relpath,
                            line=line,
                            col=0,
                            message=(
                                f"disable={rule} needs a justification: "
                                f"# graftrace: disable={rule}(why this is "
                                "safe)"
                            ),
                            qualname="<module>",
                        )
                    )


def trace_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    check_suppressions: bool = True,
) -> TraceReport:
    """Run graftrace over files/directories; returns the TraceReport
    (violations exclude properly-suppressed ones, which land in
    ``report.suppressed``)."""
    return Tracer(paths, root=root).run(check_suppressions=check_suppressions)
