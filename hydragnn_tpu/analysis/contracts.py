"""Static config/shape contract checker — ``check_config``.

Catches broken training/serving configs BEFORE any device compile: the
structural half cross-checks the JSON against the framework's config contract
(head spec vs dataset descriptors, dtype validity, bucket feasibility,
donation/distribution conflicts), and the shape half runs ``jax.eval_shape``
over the FULL stack — model init, forward, multi-head loss, and the guarded
train step — against a padded-arena example batch built from the declared
descriptors. ``eval_shape`` only traces with abstract values: nothing is
compiled, no device memory moves, and every input (batch AND rng) is passed
as a ``ShapeDtypeStruct`` so the check cannot even allocate a device array —
safe to run before ``jax.distributed.initialize`` ordering matters.

Every failure is one actionable line tagged with a stable code:

  missing-field     a key the entry point will dereference is absent
  bad-head-spec     head types/indices/weights/heads blocks disagree
  bad-arch          the Architecture block cannot build a model
  dtype-mismatch    compute_dtype is not a floating dtype
  bad-precision     Training.precision / loss_scale / serve --precision
                    nonsense (unknown arm, int8 for training, non-positive
                    scale knobs, quantized serve without a tolerance bound)
  oob-bucket        a bucket/batch/ladder size cannot hold the data
  bad-mesh          distributed/mesh config nonsense (axis sizes vs the
                    visible device count, graph_axis with the CSR/sorted
                    contract explicitly disabled, unknown grad_sync arm,
                    non-positive grad bucket size, elastic worker-range
                    knobs that cannot be satisfied) — docs/DISTRIBUTED.md
  bad-elastic-timing  elastic liveness timing that silently turns a slow
                    epoch into a hang-kill: heartbeat_s at or under the
                    pump's tick resolution (interval_s = heartbeat_s/4), or
                    heartbeat_s at or above the ProxyRendezvous wire
                    deadlines (post 10 s, barrier 300 s) — the coordinator
                    would drop a healthy worker's connection before its
                    next beat could land — docs/DISTRIBUTED.md "Elastic
                    runbook"
  bad-router        multi-replica router config nonsense (replica count /
                    hash-ring weights / admission classes without deadlines /
                    fleet ladder-memory blowout) — docs/SERVING.md
                    "Multi-replica tier"
  bad-lifecycle     live-model-lifecycle nonsense (shadow fraction outside
                    (0, 1], shadow/canary without a tolerance bound, swap
                    target whose architecture fingerprint mismatches the
                    serving config, rollback with keep_last_k < 2) —
                    docs/SERVING.md "Live model lifecycle"
  bad-flywheel      continuous-learning flywheel nonsense (auto-promotion
                    without a positive shadow tolerance, drift thresholds
                    outside (0, 1) or inverted, refit interval shorter than
                    the shadow gate window, keep_last_k < 3 with
                    auto-promotion enabled, flywheel with checkpoint_async
                    off) — docs/FLYWHEEL.md
  bad-pilot         fleet-autopilot nonsense (inverted/degenerate scale or
                    brownout watermarks, cooldown shorter than the replica
                    spin-up wall, an empty or severity-unordered brownout
                    ladder, a per-tenant quota wider than the global
                    in-flight bound, min_replicas > max_replicas) —
                    docs/SERVING.md "Fleet autopilot"
  donation-misuse   config requests a donating step that would alias buffers
  shape-mismatch    eval_shape found inconsistent shapes/dtypes end to end

Exposed as ``python -m hydragnn_tpu.analysis check-config <json>`` and called
at the top of run_training / run_prediction / serve startup.

The eval_shape pass always uses AdamW regardless of ``Training.optimizer``:
the contract being checked is model/loss/grad-step shape agreement, which is
optimizer-independent, and tracing an LBFGS linesearch would multiply the
check's cost for no additional shape coverage.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

HEAD_KINDS = ("graph", "node")


class ConfigContractError(ValueError):
    """One or more config contract violations; ``errors`` carries
    (code, message) pairs, the str() is the first message + a count."""

    def __init__(self, errors: List[Tuple[str, str]]):
        self.errors = errors
        first = f"[{errors[0][0]}] {errors[0][1]}" if errors else "config invalid"
        extra = f" (+{len(errors) - 1} more)" if len(errors) > 1 else ""
        super().__init__(first + extra)


def _get(config: Dict[str, Any], *path, default=None):
    cur: Any = config
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return default
        cur = cur[key]
    return cur


# (fingerprint, mode) -> (errors, skipped, eval_shape_s). The eval_shape half
# is pure in the model-relevant config subset, so repeated entry-point calls
# on the same config (epoch-loop tests, supervisor restarts) pay the tracing
# cost once per process.
_SHAPE_CACHE: Dict[Tuple[str, str], Tuple[list, list, Any]] = {}


def check_config(
    config,
    mode: str = "training",
    bucket_ladder: "Optional[Sequence[Tuple[int, int]] | str]" = None,
    strict: bool = True,
    deep: bool = True,
    serve_precision: Optional[str] = None,
    serve_tolerance: Optional[float] = None,
    router: Optional[Dict[str, Any]] = None,
    lifecycle: Optional[Dict[str, Any]] = None,
    flywheel: Optional[Dict[str, Any]] = None,
    pilot: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Validate a training or serving config statically. Returns the report
    dict; with ``strict`` (the default) raises :class:`ConfigContractError`
    on any violation instead. ``deep=False`` skips the ``jax.eval_shape``
    pass (structural checks only — the entry points use this when
    ``HYDRAGNN_CHECK_CONFIG=structural``). ``bucket_ladder`` accepts parsed
    ``(N_pad, E_pad)`` rungs or any CLI spec string — ``"NxE,..."`` or
    ``"auto:<path>"`` (resolved via graphs/packing.resolve_ladder_spec).
    ``serve_precision``/``serve_tolerance`` are the serve CLI's arm flags
    (docs/PRECISION.md): quantized arms without a positive tolerance bound
    are a ``bad-precision`` finding here, before the checkpoint loads.
    ``router`` is the front-router config dict (the route CLI passes
    ``{"replicas", "classes", "load_factor", "vnodes", ...}``); router
    nonsense is a ``bad-router`` finding through this same gate.
    ``lifecycle`` is the graftswap config dict
    (``{"shadow_fraction", "tolerance", "swap_target",
    "expected_fingerprint", "rollback", "keep_last_k"}``); lifecycle
    nonsense is a ``bad-lifecycle`` finding through this same gate.
    ``flywheel`` is the graftloop config dict (``FlywheelConfig.to_json()``
    or the supervisor's flywheel block: ``{"auto_promote",
    "shadow_tolerance", "drift_high", "drift_low", "refit_interval_s",
    "gate_window_s", "keep_last_k"}``); flywheel nonsense is a
    ``bad-flywheel`` finding through this same gate.
    ``pilot`` is the graftpilot config dict (``AutopilotConfig.to_json()``:
    ``{"scale_high", "scale_low", "cooldown_s", "spinup_wall_s",
    "min_replicas", "max_replicas", "ladder", "tenant_inflight_quota",
    "global_inflight_limit", ...}``); autopilot nonsense is a ``bad-pilot``
    finding through this same gate."""
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if mode not in ("training", "prediction", "serving"):
        raise ValueError(f"unknown check-config mode {mode!r}")
    errors: List[Tuple[str, str]] = []
    skipped: List[str] = []

    arch = _get(config, "NeuralNetwork", "Architecture") or {}
    voi = _get(config, "NeuralNetwork", "Variables_of_interest") or {}
    training = _get(config, "NeuralNetwork", "Training") or {}
    completed = all(k in arch for k in ("input_dim", "output_dim", "output_type"))

    _check_structure(config, arch, voi, training, mode, completed, errors)
    _check_head_spec(config, arch, voi, completed, errors)
    _check_dtype(arch, errors)
    _check_precision(
        arch, training, mode, serve_precision, serve_tolerance, errors
    )
    _check_buckets(config, arch, training, bucket_ladder, mode, errors)
    _check_mesh(training, deep, errors)
    if router is not None:
        _check_router(router, bucket_ladder, errors)
    if lifecycle is not None:
        _check_lifecycle(lifecycle, arch, training, completed, errors)
    if flywheel is not None:
        _check_flywheel(flywheel, training, errors)
    if pilot is not None:
        _check_pilot(pilot, errors)
    _check_donation(training, errors)
    _check_aggregation_path(arch, errors)

    eval_shape_s = None
    if not errors and not deep:
        skipped.append("eval_shape: disabled (deep=False)")
    elif not errors:
        key = (
            json.dumps(
                {
                    "arch": arch,
                    "voi": voi,
                    "ds": _get(config, "Dataset"),
                    # Precision changes the TRACED training step (bf16 casts
                    # + the loss-scale state machine), so it must key the
                    # shape cache too.
                    "precision": training.get("precision"),
                    "loss_scale": training.get("loss_scale"),
                },
                sort_keys=True,
                default=str,
            ),
            mode,
        )
        cached = _SHAPE_CACHE.get(key)
        if cached is not None:
            cached_errors, cached_skipped, eval_shape_s = cached
            errors.extend(cached_errors)
            skipped.extend(cached_skipped)
        else:
            shape_errors: List[Tuple[str, str]] = []
            shape_skipped: List[str] = []
            eval_shape_s = _check_shapes(
                config, arch, voi, training, mode, completed,
                shape_errors, shape_skipped,
            )
            _SHAPE_CACHE[key] = (shape_errors, shape_skipped, eval_shape_s)
            errors.extend(shape_errors)
            skipped.extend(shape_skipped)

    report = {
        "ok": not errors,
        "mode": mode,
        "completed_config": completed,
        "errors": [{"code": c, "message": m} for c, m in errors],
        "skipped": skipped,
        "eval_shape_s": eval_shape_s,
    }
    if errors and strict:
        raise ConfigContractError(errors)
    return report


def gate_config(
    config,
    mode: str = "training",
    bucket_ladder=None,
    deep=True,
    serve_precision=None,
    serve_tolerance=None,
    router=None,
    lifecycle=None,
    flywheel=None,
    pilot=None,
):
    """The ONE entry-point gate shared by run_training / run_prediction /
    serve startup: honors ``HYDRAGNN_CHECK_CONFIG`` (``full`` default,
    ``structural`` skips the eval_shape pass, ``off`` disables the gate) and
    raises :class:`ConfigContractError` with one actionable line on a broken
    config — before data loading and before any device compile."""
    import os

    level = os.environ.get("HYDRAGNN_CHECK_CONFIG", "full")
    if level == "off":
        return None
    return check_config(
        config,
        mode=mode,
        bucket_ladder=bucket_ladder,
        deep=deep and level != "structural",
        serve_precision=serve_precision,
        serve_tolerance=serve_tolerance,
        router=router,
        lifecycle=lifecycle,
        flywheel=flywheel,
        pilot=pilot,
    )


# ------------------------------------------------------------------ structure
def _check_structure(config, arch, voi, training, mode, completed, errors):
    if not isinstance(_get(config, "NeuralNetwork"), dict):
        errors.append(("missing-field", "config has no NeuralNetwork block"))
        return
    for key in ("model_type", "hidden_dim", "num_conv_layers", "output_heads",
                "task_weights"):
        if key not in arch:
            errors.append(
                ("missing-field", f"NeuralNetwork.Architecture.{key} is missing")
            )
    if mode == "serving":
        if not completed:
            missing = [
                k
                for k in ("input_dim", "output_dim", "output_type")
                if k not in arch
            ]
            errors.append(
                (
                    "missing-field",
                    "serving needs a COMPLETED config (missing Architecture."
                    + "/".join(missing)
                    + ") — pass the logs/<name>/config.json snapshot "
                    "run_training wrote, not the raw input config",
                )
            )
        return
    # training mode: the data-driven completion contract needs these.
    if _get(config, "Verbosity", "level") is None:
        errors.append(("missing-field", "Verbosity.level is missing"))
    ds = _get(config, "Dataset")
    if not isinstance(ds, dict):
        errors.append(
            ("missing-field", "Dataset block is missing (training mode "
             "loads and splits from Dataset.path)")
        )
    else:
        for key in ("name", "path"):
            if key not in ds:
                errors.append(("missing-field", f"Dataset.{key} is missing"))
        if isinstance(ds.get("path"), dict) and not ds["path"]:
            errors.append(("missing-field", "Dataset.path is empty"))
        kinds_used = set(voi.get("type") or ())
        for kind in ("graph", "node"):
            feat = f"{kind}_features"
            if kind in kinds_used and not completed:
                if not isinstance(_get(ds, feat, "dim"), list):
                    errors.append(
                        (
                            "missing-field",
                            f"Dataset.{feat}.dim is missing but the config "
                            f"declares a {kind!r} head — completion cannot "
                            "derive its output width",
                        )
                    )
    for key in ("input_node_features", "type", "output_index"):
        # Completed configs may omit type/output_index (Architecture carries
        # output_type/output_dim) but never input_node_features.
        if key not in voi and not (completed and key != "input_node_features"):
            errors.append(
                (
                    "missing-field",
                    f"NeuralNetwork.Variables_of_interest.{key} is missing",
                )
            )
    # batch_size feeds the loaders on every entry point; the epoch-loop
    # knobs only matter when a training loop will actually run.
    required_training = (
        ("batch_size",)
        if mode == "prediction"
        else ("batch_size", "learning_rate", "num_epoch")
    )
    for key in required_training:
        if key not in training:
            errors.append(
                ("missing-field", f"NeuralNetwork.Training.{key} is missing")
            )


# ------------------------------------------------------------------ head spec
def _check_head_spec(config, arch, voi, completed, errors):
    types = list(
        arch.get("output_type") if completed else (voi.get("type") or ())
    )
    if not types:
        return
    bad_kinds = [t for t in types if t not in HEAD_KINDS]
    if bad_kinds:
        errors.append(
            (
                "bad-head-spec",
                f"unknown head kind(s) {bad_kinds} — every entry of "
                "Variables_of_interest.type must be 'graph' or 'node'",
            )
        )
    indices = voi.get("output_index")
    if indices is not None and len(indices) != len(types):
        errors.append(
            (
                "bad-head-spec",
                f"{len(types)} head type(s) but {len(indices)} "
                "output_index entries — the lists must be parallel",
            )
        )
    weights = arch.get("task_weights")
    if isinstance(weights, list) and len(weights) != len(types):
        errors.append(
            (
                "bad-head-spec",
                f"task_weights has {len(weights)} entries for {len(types)} "
                "head(s) — one loss weight per head",
            )
        )
    heads = arch.get("output_heads")
    if isinstance(heads, dict):
        for kind in sorted(set(types) & set(HEAD_KINDS)):
            if kind not in heads:
                errors.append(
                    (
                        "bad-head-spec",
                        f"config declares a {kind!r} head but "
                        f"Architecture.output_heads has no {kind!r} block",
                    )
                )
    # Mirrors completion's _stage_edge_dim assertion, but as one line up
    # front: only the edge-consuming conv stacks accept edge_features.
    if arch.get("edge_features") and arch.get("model_type") not in (
        "PNA",
        "CGCNN",
    ):
        errors.append(
            (
                "bad-arch",
                f"Architecture.edge_features declared but model_type "
                f"{arch.get('model_type')!r} does not consume per-edge "
                "features (PNA/CGCNN only)",
            )
        )
    if completed:
        dims = arch.get("output_dim") or []
        if len(dims) != len(types):
            errors.append(
                (
                    "bad-head-spec",
                    f"completed config disagrees with itself: {len(dims)} "
                    f"output_dim entries for {len(types)} output_type entries",
                )
            )
    elif indices is not None and isinstance(_get(config, "Dataset"), dict):
        for kind in HEAD_KINDS:
            dims = _get(config, "Dataset", f"{kind}_features", "dim")
            if not isinstance(dims, list):
                continue
            for i, (t, idx) in enumerate(zip(types, indices)):
                if t == kind and not (
                    isinstance(idx, int) and 0 <= idx < len(dims)
                ):
                    errors.append(
                        (
                            "bad-head-spec",
                            f"head {i}: output_index {idx} is outside "
                            f"Dataset.{kind}_features.dim (len {len(dims)})",
                        )
                    )


# ---------------------------------------------------------------------- dtype
def _check_dtype(arch, errors):
    cd = arch.get("compute_dtype")
    if cd is None:
        return
    import numpy as np

    try:
        dt = np.dtype(
            {"bfloat16": np.float32}.get(cd, cd)
        )  # np has no bfloat16; jnp accepts it — validate the rest via numpy
        is_float = np.issubdtype(dt, np.floating) or cd == "bfloat16"
    except TypeError:
        errors.append(
            (
                "dtype-mismatch",
                f"Architecture.compute_dtype {cd!r} is not a dtype",
            )
        )
        return
    if not is_float:
        errors.append(
            (
                "dtype-mismatch",
                f"Architecture.compute_dtype {cd!r} is not a floating dtype "
                "— mixed-precision compute must be float (e.g. 'bfloat16')",
            )
        )


# ------------------------------------------------------------------ precision
def _check_precision(
    arch, training, mode, serve_precision, serve_tolerance, errors
):
    """graftprec config contract (docs/PRECISION.md): unknown precision
    strings, int8 for TRAINING, loss-scale knob nonsense, and quantized
    serving without a tolerance bound are one actionable line here — before
    the checkpoint loads or the first step compiles."""
    from ..precision.policy import (
        QUANTIZED_SERVE_PRECISIONS,
        SERVE_PRECISIONS,
        TRAIN_PRECISIONS,
        LossScaleConfig,
    )

    if mode == "serving":
        if serve_precision is None:
            return
        if serve_precision not in SERVE_PRECISIONS:
            errors.append(
                (
                    "bad-precision",
                    f"serving precision {serve_precision!r} is not one of "
                    f"{SERVE_PRECISIONS}",
                )
            )
        elif serve_precision in QUANTIZED_SERVE_PRECISIONS:
            if not isinstance(serve_tolerance, (int, float)) or isinstance(
                serve_tolerance, bool
            ) or serve_tolerance <= 0:
                errors.append(
                    (
                        "bad-precision",
                        f"quantized serving (--precision {serve_precision}) "
                        "requires a positive --tolerance bound — the "
                        "bit-exactness contract is relaxed, never silently "
                        f"dropped; got {serve_tolerance!r}",
                    )
                )
        elif serve_tolerance is not None:
            errors.append(
                (
                    "bad-precision",
                    "--tolerance is a quantized-arm knob; --precision f32 "
                    "serves under the bit-exactness contract and accepts "
                    "none",
                )
            )
        return
    prec = training.get("precision")
    if prec is not None:
        if prec == "int8":
            errors.append(
                (
                    "bad-precision",
                    "Training.precision='int8' is not a training mode — "
                    "int8 is a quantized SERVING arm (--precision int8); "
                    "train with 'bf16' and quantize at serve time",
                )
            )
        elif prec not in TRAIN_PRECISIONS:
            errors.append(
                (
                    "bad-precision",
                    f"Training.precision {prec!r} is not one of "
                    f"{TRAIN_PRECISIONS}",
                )
            )
        elif prec == "f32" and arch.get("compute_dtype") == "bfloat16":
            errors.append(
                (
                    "bad-precision",
                    "Training.precision='f32' contradicts "
                    "Architecture.compute_dtype='bfloat16' — drop one (the "
                    "policy would silently not be full f32)",
                )
            )
        elif prec == "bf16" and arch.get("compute_dtype") not in (
            None,
            "bfloat16",
        ):
            # The other direction of the same contradiction: the driver only
            # clones onto bf16 compute when compute_dtype is unset, so an
            # explicit non-bf16 dtype would silently train at THAT dtype
            # with pointless loss scaling armed.
            errors.append(
                (
                    "bad-precision",
                    "Training.precision='bf16' contradicts "
                    f"Architecture.compute_dtype="
                    f"{arch.get('compute_dtype')!r} — bf16 training needs "
                    "compute_dtype unset (the policy sets it) or 'bfloat16'",
                )
            )
        if (
            prec == "bf16"
            and str(training.get("optimizer", "")).upper() == "LBFGS"
        ):
            errors.append(
                (
                    "bad-precision",
                    "Training.precision='bf16' (dynamic loss scaling) does "
                    "not support LBFGS — the zoom linesearch is not "
                    "scale-invariant under dynamic rescaling; use a "
                    "first-order optimizer",
                )
            )
    ls = training.get("loss_scale")
    if ls is None:
        return
    if not isinstance(ls, dict):
        errors.append(
            (
                "bad-precision",
                f"Training.loss_scale must be a dict of scale knobs "
                f"(init/backoff/growth/growth_interval), got "
                f"{type(ls).__name__}",
            )
        )
        return
    try:
        LossScaleConfig.from_config(ls)
    except (TypeError, ValueError) as e:
        errors.append(
            ("bad-precision", f"Training.loss_scale is invalid: {e}")
        )


# -------------------------------------------------------------------- buckets
def _check_router(router, bucket_ladder, errors):
    """Front-router config contract (docs/SERVING.md "Multi-replica tier"):
    replica-count / hash-ring-weight / admission-class nonsense and a
    fleet-wide ladder-memory blowout (every replica compiles or hydrates
    the WHOLE bucket ladder — N replicas x R rungs executables resident)
    are one actionable ``bad-router`` line before any engine is built."""
    import math

    replicas = router.get("replicas", 1)
    n_replicas = None
    if isinstance(replicas, int) and not isinstance(replicas, bool):
        n_replicas = replicas
        if replicas < 1:
            errors.append(
                (
                    "bad-router",
                    f"router needs at least 1 replica, got {replicas}",
                )
            )
    elif isinstance(replicas, (list, tuple)):
        n_replicas = len(replicas)
        if not replicas:
            errors.append(("bad-router", "router replica list is empty"))
        for i, spec in enumerate(replicas):
            weight = (
                spec.get("weight", 1.0) if isinstance(spec, dict) else spec
            )
            try:
                w = float(weight)
            except (TypeError, ValueError):
                w = float("nan")
            if not math.isfinite(w) or w <= 0:
                errors.append(
                    (
                        "bad-router",
                        f"replica #{i} hash-ring weight must be a positive "
                        f"finite number, got {weight!r}",
                    )
                )
    else:
        errors.append(
            (
                "bad-router",
                f"router 'replicas' must be a count or a list, got "
                f"{type(replicas).__name__}",
            )
        )

    classes = router.get("classes")
    if classes is not None:
        if not isinstance(classes, dict) or not classes:
            errors.append(
                (
                    "bad-router",
                    "router 'classes' must be a non-empty mapping of "
                    "admission-class name -> {deadline_s}",
                )
            )
        else:
            for name, spec in classes.items():
                deadline = (
                    spec.get("deadline_s")
                    if isinstance(spec, dict)
                    else spec
                )
                try:
                    d = float(deadline)
                except (TypeError, ValueError):
                    d = float("nan")
                if not math.isfinite(d) or d <= 0:
                    errors.append(
                        (
                            "bad-router",
                            f"admission class {name!r} has no positive "
                            f"finite deadline_s (got {deadline!r}) — an SLO "
                            "class without a deadline cannot shed load",
                        )
                    )

    load_factor = router.get("load_factor", 1.25)
    try:
        lf = float(load_factor)
    except (TypeError, ValueError):
        lf = float("nan")
    if not math.isfinite(lf) or lf < 1.0:
        errors.append(
            (
                "bad-router",
                f"load_factor must be a finite number >= 1 (bounded-load "
                f"consistent hashing), got {load_factor!r}",
            )
        )

    vnodes = router.get("vnodes", 64)
    if not isinstance(vnodes, int) or isinstance(vnodes, bool) or vnodes < 1:
        errors.append(
            ("bad-router", f"vnodes must be an integer >= 1, got {vnodes!r}")
        )

    # Fleet ladder memory: resolve the rung count when a ladder is known.
    rungs = None
    if isinstance(bucket_ladder, str):
        try:
            from ..graphs.packing import resolve_ladder_spec

            rungs = len(resolve_ladder_spec(bucket_ladder))
        except Exception:  # noqa: BLE001 — _check_buckets reports the spec
            rungs = None
    elif bucket_ladder is not None:
        try:
            rungs = len(list(bucket_ladder))
        except TypeError:
            rungs = None
    max_fleet_buckets = router.get("max_fleet_buckets", 128)
    if (
        not isinstance(max_fleet_buckets, int)
        or isinstance(max_fleet_buckets, bool)
        or max_fleet_buckets < 1
    ):
        errors.append(
            (
                "bad-router",
                "max_fleet_buckets must be an integer >= 1, got "
                f"{max_fleet_buckets!r}",
            )
        )
        max_fleet_buckets = 128
    if rungs and n_replicas and n_replicas * rungs > max_fleet_buckets:
        errors.append(
            (
                "bad-router",
                f"{n_replicas} replicas x {rungs} ladder rungs = "
                f"{n_replicas * rungs} resident executables exceeds the "
                f"fleet budget {max_fleet_buckets} — shrink the ladder, "
                "the fleet, or raise router.max_fleet_buckets",
            )
        )


def _expected_param_fingerprint(arch) -> Optional[str]:
    """Param-tree fingerprint of the (completed) serving config's model,
    via ``jax.eval_shape`` over ``model.init`` — ShapeDtypeStructs only, so
    nothing compiles and no device memory moves (the same zero-allocation
    discipline as the eval_shape gate). The fingerprint hashes key paths /
    shapes / dtypes, which SDS leaves carry."""
    import jax
    import numpy as np

    from ..checkpoint.format import param_fingerprint
    from ..models.create import create_model_config, make_example_batch

    arch2 = dict(arch)
    arch2.setdefault("freeze_conv_layers", False)
    model = create_model_config(config=arch2, verbosity=0)
    example = make_example_batch(
        arch["input_dim"],
        arch["output_dim"],
        arch["output_type"],
        edge_dim=arch.get("edge_dim"),
        num_nodes=int(arch.get("num_nodes") or 8),
    )
    batch_sds = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        example,
    )
    key_sds = jax.ShapeDtypeStruct((2,), np.uint32)
    variables = jax.eval_shape(
        lambda b, k: model.init({"params": k, "dropout": k}, b, train=False),
        batch_sds,
        key_sds,
    )
    return param_fingerprint(variables["params"])


def _check_lifecycle(lifecycle, arch, training, completed, errors):
    """graftswap config contract (docs/SERVING.md "Live model lifecycle"):
    shadow-fraction / tolerance / rollback-retention / swap-target nonsense
    is one actionable ``bad-lifecycle`` line before any engine mutates."""
    import math

    frac = lifecycle.get("shadow_fraction")
    if frac is not None:
        try:
            f = float(frac)
        except (TypeError, ValueError):
            f = float("nan")
        if not math.isfinite(f) or not (0.0 < f <= 1.0):
            errors.append(
                (
                    "bad-lifecycle",
                    f"shadow fraction must be in (0, 1], got {frac!r} — 0 "
                    "mirrors nothing (the gate can never go green) and >1 "
                    "is not a sampling fraction",
                )
            )
        tol = lifecycle.get("tolerance")
        if (
            not isinstance(tol, (int, float))
            or isinstance(tol, bool)
            or not math.isfinite(float(tol))
            or tol <= 0
        ):
            errors.append(
                (
                    "bad-lifecycle",
                    "shadow/canary serving requires a positive tolerance "
                    "bound (the diff gate's definition of 'matches live'); "
                    f"got {tol!r}",
                )
            )
    if lifecycle.get("rollback"):
        k = lifecycle.get(
            "keep_last_k", training.get("checkpoint_keep_last_k")
        )
        if not isinstance(k, int) or isinstance(k, bool) or k < 2:
            errors.append(
                (
                    "bad-lifecycle",
                    f"rollback requires checkpoint_keep_last_k >= 2 (got "
                    f"{k!r}) — the previous version must still exist in the "
                    "retention manifest to be restorable",
                )
            )
    target = lifecycle.get("swap_target")
    if target:
        fp = None
        try:
            from ..checkpoint.format import file_content_identity

            _identity, header = file_content_identity(str(target))
            fp = header.get("param_fingerprint")
        except Exception as e:  # noqa: BLE001 — every read failure is a finding
            errors.append(
                (
                    "bad-lifecycle",
                    f"swap target {target!r} is not a verifiable v2 "
                    f"checkpoint: {e}",
                )
            )
        if fp:
            expected = lifecycle.get("expected_fingerprint")
            if expected is None and completed:
                try:
                    expected = _expected_param_fingerprint(arch)
                except Exception:  # noqa: BLE001 — bad-arch reported elsewhere
                    expected = None
            if expected and fp != expected:
                errors.append(
                    (
                        "bad-lifecycle",
                        f"swap target {target!r} was saved from a different "
                        "architecture than the serving config (param-tree "
                        "fingerprint mismatch) — a hot swap is weights-only; "
                        "an architecture change needs a replica rebuild",
                    )
                )


def _check_flywheel(flywheel, training, errors):
    """graftloop config contract (docs/FLYWHEEL.md): a misconfigured
    flywheel does not fail loudly — it silently promotes garbage (no
    tolerance), flaps the ladder (inverted thresholds), starves its own
    shadow gate (refit < gate window), or GC-races its rollback chain
    (keep_last_k < 3). Each is one actionable ``bad-flywheel`` line before
    the control thread starts."""
    import math

    def _num(key):
        v = flywheel.get(key)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        f = float(v)
        return f if math.isfinite(f) else None

    auto = bool(flywheel.get("auto_promote", True))
    tol = _num("shadow_tolerance")
    if auto and (tol is None or tol <= 0):
        errors.append(
            (
                "bad-flywheel",
                "auto-promotion requires a positive shadow_tolerance — "
                "without a diff bound the shadow gate has no definition of "
                "'candidate matches live' and promotion is unguarded; got "
                f"{flywheel.get('shadow_tolerance')!r}",
            )
        )
    high = _num("drift_high")
    low = _num("drift_low")
    for key, val in (("drift_high", high), ("drift_low", low)):
        if flywheel.get(key) is not None and (
            val is None or not (0.0 < val < 1.0)
        ):
            errors.append(
                (
                    "bad-flywheel",
                    f"{key} must be in (0, 1) — histogram distance is "
                    "total-variation, so 0 fires on any noise and >= 1 can "
                    f"never fire; got {flywheel.get(key)!r}",
                )
            )
    if high is not None and low is not None and not (low < high):
        errors.append(
            (
                "bad-flywheel",
                f"drift thresholds must satisfy low < high (got low={low!r} "
                f"high={high!r}) — equal or inverted thresholds remove the "
                "hysteresis band and the refit actuator can flap on "
                "boundary noise",
            )
        )
    refit = _num("refit_interval_s")
    gate_w = _num("gate_window_s")
    if refit is not None and gate_w is not None and refit < gate_w:
        errors.append(
            (
                "bad-flywheel",
                f"refit_interval_s ({refit!r}) must be >= gate_window_s "
                f"({gate_w!r}) — re-evaluating drift faster than the shadow "
                "gate can accumulate samples lets a ladder swap land "
                "mid-judgement and invalidate the gate's comparisons",
            )
        )
    if auto:
        k = flywheel.get(
            "keep_last_k", training.get("checkpoint_keep_last_k")
        )
        if isinstance(k, int) and not isinstance(k, bool) and k < 3:
            errors.append(
                (
                    "bad-flywheel",
                    f"auto-promotion requires checkpoint_keep_last_k >= 3 "
                    f"(got {k!r}) — live, previous, and the in-flight "
                    "candidate each need a retained slot or retention GC "
                    "races the promotion it is feeding",
                )
            )
    ckpt_async = flywheel.get(
        "checkpoint_async", training.get("checkpoint_async")
    )
    if ckpt_async is not None and not ckpt_async:
        errors.append(
            (
                "bad-flywheel",
                "the flywheel requires checkpoint_async — its staging hook "
                "rides the async saver's post-save callback, and a "
                "synchronous save would stall the training step for the "
                "full stage-and-arm round trip",
            )
        )


def _check_pilot(pilot, errors):
    """graftpilot config contract (docs/SERVING.md "Fleet autopilot"): a
    misconfigured autopilot does not fail loudly — it flaps the fleet
    (inverted watermarks), double-scales every wave (cooldown shorter than
    the spin-up wall), browns out the HIGHEST-priority class first (an
    unordered ladder), or lets one tenant fill the whole router (quota
    wider than the global bound). Each is one actionable ``bad-pilot``
    line before the control thread starts. Mirrors
    ``pilot.AutopilotConfig.__post_init__`` — what the gate rejects, the
    constructor rejects too."""
    import math

    def _num(key):
        v = pilot.get(key)
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            return None
        f = float(v)
        return f if math.isfinite(f) else None

    for low_key, high_key in (
        ("scale_low", "scale_high"),
        ("brownout_low", "brownout_high"),
    ):
        low, high = _num(low_key), _num(high_key)
        present = pilot.get(low_key) is not None or pilot.get(high_key) is not None
        if present and (
            low is None or high is None or not (0.0 <= low < high)
        ):
            errors.append(
                (
                    "bad-pilot",
                    f"{low_key}/{high_key} must satisfy 0 <= low < high "
                    f"(got {pilot.get(low_key)!r}/{pilot.get(high_key)!r}) — "
                    "an inverted or degenerate pair removes the dead band "
                    "and the autoscaler flaps on boundary noise",
                )
            )
    cooldown = _num("cooldown_s")
    spinup = _num("spinup_wall_s")
    if cooldown is not None and spinup is not None and cooldown < spinup:
        errors.append(
            (
                "bad-pilot",
                f"cooldown_s ({cooldown!r}) must cover spinup_wall_s "
                f"({spinup!r}) — re-deciding while the previous replica is "
                "still warming double-scales on every wave",
            )
        )
    ladder = pilot.get("ladder")
    if ladder is not None:
        from ..pilot.brownout import parse_ladder

        try:
            parse_ladder(ladder)
        except (ValueError, TypeError) as e:
            errors.append(("bad-pilot", f"brownout ladder invalid: {e}"))
    quota = _num("tenant_inflight_quota")
    bound = _num("global_inflight_limit")
    if quota is not None and bound is not None and quota > bound:
        errors.append(
            (
                "bad-pilot",
                f"tenant_inflight_quota ({quota!r}) exceeds "
                f"global_inflight_limit ({bound!r}) — one tenant's bulkhead "
                "would be wide enough to fill the whole fleet, which is no "
                "bulkhead at all",
            )
        )
    mn = _num("min_replicas")
    mx = _num("max_replicas")
    if mn is not None and mn < 0:
        errors.append(
            ("bad-pilot", f"min_replicas must be >= 0, got {mn!r}")
        )
    if mx is not None and mx < 1:
        errors.append(
            ("bad-pilot", f"max_replicas must be >= 1, got {mx!r}")
        )
    if mn is not None and mx is not None and mn > mx:
        errors.append(
            (
                "bad-pilot",
                f"min_replicas ({mn!r}) > max_replicas ({mx!r}) — the "
                "reconciler's clamp range is empty and the target is "
                "undefined",
            )
        )
    idle = _num("idle_ticks_to_zero")
    if idle is not None and idle > 0 and mn is not None and mn != 0:
        errors.append(
            (
                "bad-pilot",
                f"idle_ticks_to_zero ({idle!r}) requires min_replicas == 0 "
                f"(got {mn!r}) — scale-to-zero retires the whole fleet",
            )
        )


def _check_buckets(config, arch, training, bucket_ladder, mode, errors):
    bs = training.get("batch_size")
    if bs is not None and (not isinstance(bs, int) or bs < 1):
        errors.append(
            ("oob-bucket", f"Training.batch_size {bs!r} must be an int >= 1")
        )
    nb = _get(config, "Dataset", "num_buckets")
    if nb is not None and (not isinstance(nb, int) or nb < 1):
        errors.append(
            ("oob-bucket", f"Dataset.num_buckets {nb!r} must be an int >= 1")
        )
    ls = _get(config, "Dataset", "ladder_step")
    if ls is not None and ls not in ("pow2", "mult64"):
        errors.append(
            (
                "oob-bucket",
                f"Dataset.ladder_step {ls!r} must be 'pow2' or 'mult64' "
                "(the pad round-up ladder, graphs/packing.round_up_step)",
            )
        )
    pk = _get(config, "Dataset", "packing")
    if pk is not None and not isinstance(pk, bool):
        errors.append(
            ("oob-bucket", f"Dataset.packing {pk!r} must be a bool")
        )
    if isinstance(bucket_ladder, str):
        # Spec forms ("NxE,..." literal, "auto:<histogram-or-ladder.json>")
        # resolve through ONE parser so CLI and checker can never disagree;
        # any resolution failure (bad literal, missing/garbled auto file,
        # empty histogram) is an actionable oob-bucket line here instead of
        # a stack trace after the checkpoint loaded.
        from ..graphs.packing import resolve_ladder_spec

        try:
            bucket_ladder = resolve_ladder_spec(bucket_ladder)
        except Exception as e:  # noqa: BLE001 — every parse error is a finding
            errors.append(
                (
                    "oob-bucket",
                    f"bucket ladder spec {bucket_ladder!r} is invalid: {e}",
                )
            )
            bucket_ladder = None
    if bucket_ladder is not None:
        num_nodes = arch.get("num_nodes")
        best_n = 0
        for rung in bucket_ladder:
            # Explicit pair check first: a stray string would otherwise index
            # as its characters ("64" -> (6, 4)) and mis-validate.
            if not isinstance(rung, (tuple, list)) or len(rung) != 2:
                errors.append(
                    ("oob-bucket", f"bucket ladder rung {rung!r} is not (N_pad, E_pad)")
                )
                continue
            try:
                n, e = int(rung[0]), int(rung[1])
            except (TypeError, ValueError):
                errors.append(
                    ("oob-bucket", f"bucket ladder rung {rung!r} is not (N_pad, E_pad)")
                )
                continue
            if n < 2 or e < 1:
                errors.append(
                    (
                        "oob-bucket",
                        f"bucket ladder rung ({n}, {e}) cannot hold a graph "
                        "(N_pad needs >= 1 real + 1 padding node)",
                    )
                )
            best_n = max(best_n, n)
        if num_nodes and best_n and best_n <= int(num_nodes):
            errors.append(
                (
                    "oob-bucket",
                    f"largest bucket ladder rung N_pad={best_n} cannot fit a "
                    f"single num_nodes={num_nodes} graph (collate needs "
                    "N_pad > total nodes)",
                )
            )
    ga = training.get("graph_axis")
    if ga is not None and (not isinstance(ga, int) or ga < 1):
        errors.append(
            ("oob-bucket", f"Training.graph_axis {ga!r} must be an int >= 1")
        )


# ----------------------------------------------------------------- mesh/graftmesh
def _check_mesh(training, deep, errors):
    """graftmesh config contract (docs/DISTRIBUTED.md): mesh-axis requests
    the visible devices cannot satisfy, a graph-partitioned run with the
    CSR/sorted aggregation contract explicitly disabled, unknown
    gradient-sync arms, nonsense bucket sizes, and unsatisfiable elastic
    worker ranges are one actionable ``bad-mesh`` line each — before any
    mesh builds or a shard_map step compiles.

    bf16 + mesh is deliberately NOT a finding since graftmesh: the
    loss-scale state machine rides the mesh step with the backoff update in
    lockstep post-psum (train/trainer._dp_local_graftmesh), closing ROADMAP
    item 3's explicit rejection.

    The device-count comparison runs only under ``deep`` — counting devices
    initializes the XLA backend, which the structural-only gate (the
    supervisor's pre-spawn path) must never do."""
    import os

    ga = training.get("graph_axis")
    ga = ga if isinstance(ga, int) and ga >= 1 else 1
    if ga > 1 and os.environ.get("HYDRAGNN_SEGMENT_SORTED") in (
        "0", "false", "False",
    ):
        errors.append(
            (
                "bad-mesh",
                f"Training.graph_axis={ga} with HYDRAGNN_SEGMENT_SORTED "
                "disabled: graph-partitioned training's halo/edge-cut "
                "exchange is built on the CSR/sorted contract "
                "(ops localize row_ptr per edge shard) — re-enable the "
                "sorted path or drop graph_axis",
            )
        )
    if ga > 1 and deep:
        import jax

        n = jax.device_count()
        if ga > n:
            errors.append(
                (
                    "bad-mesh",
                    f"Training.graph_axis={ga} exceeds the {n} visible "
                    "device(s) — the mesh cannot build; shrink graph_axis "
                    "or pin more virtual devices "
                    "(XLA_FLAGS=--xla_force_host_platform_device_count)",
                )
            )
    gs = training.get("grad_sync")
    if gs is not None:
        from ..parallel.overlap import GRAD_SYNC_MODES

        if gs not in GRAD_SYNC_MODES:
            errors.append(
                (
                    "bad-mesh",
                    f"Training.grad_sync {gs!r} is not one of "
                    f"{GRAD_SYNC_MODES}",
                )
            )
    gbm = training.get("grad_bucket_mb")
    if gbm is not None and (
        isinstance(gbm, bool)
        or not isinstance(gbm, (int, float))
        or gbm <= 0
    ):
        errors.append(
            (
                "bad-mesh",
                f"Training.grad_bucket_mb {gbm!r} must be a positive number "
                "(megabytes per gradient all-reduce bucket)",
            )
        )
    elastic = training.get("elastic")
    if elastic is None:
        return
    if not isinstance(elastic, dict):
        errors.append(
            (
                "bad-mesh",
                "Training.elastic must be a dict of worker-range knobs "
                f"(min_workers/max_workers/heartbeat_s), got "
                f"{type(elastic).__name__}",
            )
        )
        return
    unknown = sorted(
        set(elastic) - {"min_workers", "max_workers", "heartbeat_s"}
    )
    if unknown:
        errors.append(
            ("bad-mesh", f"Training.elastic has unknown knob(s) {unknown}")
        )
    mn, mx = elastic.get("min_workers", 1), elastic.get("max_workers")
    bounds_ok = True
    for name, val in (("min_workers", mn), ("max_workers", mx)):
        if val is not None and (
            isinstance(val, bool) or not isinstance(val, int) or val < 1
        ):
            errors.append(
                (
                    "bad-mesh",
                    f"Training.elastic.{name} {val!r} must be an int >= 1",
                )
            )
            bounds_ok = False
    if bounds_ok and mx is not None and mn is not None and mn > mx:
        errors.append(
            (
                "bad-mesh",
                f"Training.elastic min_workers={mn} > max_workers={mx} — "
                "no world size satisfies the range",
            )
        )
    hb = elastic.get("heartbeat_s")
    if hb is not None and (
        isinstance(hb, bool) or not isinstance(hb, (int, float)) or hb <= 0
    ):
        errors.append(
            (
                "bad-mesh",
                f"Training.elastic.heartbeat_s {hb!r} must be a positive "
                "number of seconds",
            )
        )
    elif hb is not None:
        # Liveness timing (bad-elastic-timing): the HeartbeatPump posts
        # every heartbeat_s/4 and the supervisor declares a worker dead
        # after ~heartbeat_s without a beat, while the ProxyRendezvous wire
        # path enforces its own read/write deadlines. A heartbeat window
        # that does not fit strictly inside those deadlines (or a pump tick
        # below timer resolution) silently turns every slow epoch into a
        # hang-kill — flag it here, before any worker spawns.
        from ..parallel.loopback import _BARRIER_TIMEOUT_S, _POST_TIMEOUT_S

        pump_s = hb / 4.0
        if pump_s < 0.05:
            errors.append(
                (
                    "bad-elastic-timing",
                    f"Training.elastic.heartbeat_s={hb} puts the heartbeat "
                    f"pump interval at {pump_s:.3g}s (heartbeat_s/4) — "
                    "below timer resolution, the pump cannot hold the "
                    "margin; raise heartbeat_s to at least 0.2",
                )
            )
        if hb >= _POST_TIMEOUT_S:
            errors.append(
                (
                    "bad-elastic-timing",
                    f"Training.elastic.heartbeat_s={hb} is not strictly "
                    f"under the ProxyRendezvous post deadline "
                    f"({_POST_TIMEOUT_S:g}s) — a beat delayed by one slow "
                    "post RPC overshoots the liveness window and the "
                    "supervisor kills a healthy worker",
                )
            )
        if hb >= _BARRIER_TIMEOUT_S:
            errors.append(
                (
                    "bad-elastic-timing",
                    f"Training.elastic.heartbeat_s={hb} is not strictly "
                    f"under the ProxyRendezvous barrier deadline "
                    f"({_BARRIER_TIMEOUT_S:g}s) — the rendezvous would time "
                    "out a world that is merely waiting for the next "
                    "heartbeat-paced quiesce",
                )
            )


# ---------------------------------------------------------- aggregation path
def _check_aggregation_path(arch, errors):
    """Reject configs whose resolved conv family cannot ride the sorted/CSR
    edge layout (models/convs.py:SORTED_PATH_FAMILIES). On TPU the sorted
    path is the DEFAULT (ops/segment_sorted.sorted_enabled) — a family
    outside the registry would silently fall back to the unsorted XLA
    scatter path, the exact regression class BENCH_r05 measured at 0.47x.
    Every shipped family is registered since PR 7 (GAT joined via the
    self-term rework); this check exists so a future family cannot land
    half-ported without an explicit opt-out."""
    import os

    mt = arch.get("model_type")
    if mt is None:
        return  # missing-field already reported
    from ..models.base import CONV_TYPES
    from ..models.convs import SORTED_PATH_FAMILIES

    if mt not in CONV_TYPES:
        return  # bad-arch surfaces at model build; don't double-report
    if mt in SORTED_PATH_FAMILIES:
        return
    if os.environ.get("HYDRAGNN_SEGMENT_SORTED") in ("0", "false", "False"):
        return  # the sorted path is explicitly pinned off — scatter is intended
    errors.append(
        (
            "bad-arch",
            f"model_type {mt!r} is not registered in SORTED_PATH_FAMILIES "
            "(models/convs.py): on TPU its aggregation would silently fall "
            "back to the unsorted scatter path — register the family's "
            "sorted/CSR aggregation or pin HYDRAGNN_SEGMENT_SORTED=0",
        )
    )


# ------------------------------------------------------------------- donation
def _check_donation(training, errors):
    if str(training.get("optimizer", "")).upper() == "LBFGS" and int(
        training.get("graph_axis") or 1
    ) > 1:
        errors.append(
            (
                "donation-misuse",
                "Training.optimizer=LBFGS stores the params pytree in its "
                "state (aliased buffers) — the distributed donating step "
                "cannot run; use a first-order optimizer or drop graph_axis",
            )
        )


# ----------------------------------------------------------------- eval_shape
def _derive_model_spec(config, arch, voi, completed, errors, skipped):
    """(input_dim, output_dim, output_type, edge_dim, num_nodes) or None."""
    if completed:
        return (
            int(arch["input_dim"]),
            [int(d) for d in arch["output_dim"]],
            list(arch["output_type"]),
            arch.get("edge_dim"),
            int(arch.get("num_nodes") or 8),
        )
    types = voi.get("type")
    indices = voi.get("output_index")
    inputs = voi.get("input_node_features")
    if not (types and indices is not None and inputs):
        skipped.append("eval_shape: head spec underivable from this config")
        return None
    dims = []
    for t, idx in zip(types, indices):
        table = _get(config, "Dataset", f"{t}_features", "dim")
        if not isinstance(table, list) or not (0 <= int(idx) < len(table)):
            skipped.append(
                "eval_shape: Dataset descriptors do not cover the head spec"
            )
            return None
        dims.append(int(table[int(idx)]))
    edge_features = arch.get("edge_features")
    if edge_features:
        edge_dim = len(edge_features)
    elif arch.get("model_type") == "CGCNN":
        edge_dim = 0
    else:
        edge_dim = None
    return len(inputs), dims, list(types), edge_dim, int(arch.get("num_nodes") or 8)


def _check_shapes(config, arch, voi, training, mode, completed, errors, skipped):
    spec = _derive_model_spec(config, arch, voi, completed, errors, skipped)
    if spec is None:
        return None
    input_dim, output_dim, output_type, edge_dim, num_nodes = spec

    t0 = time.perf_counter()
    import jax
    import numpy as np

    from ..models.create import create_model_config, make_example_batch

    arch2 = dict(arch)
    arch2.update(
        input_dim=input_dim,
        output_dim=output_dim,
        output_type=output_type,
        edge_dim=edge_dim,
        num_nodes=num_nodes,
    )
    arch2.setdefault("freeze_conv_layers", False)
    if arch2.get("model_type") == "PNA" and not arch2.get("pna_deg"):
        mn = arch2.get("max_neighbours")
        if mn is None:
            errors.append(
                (
                    "bad-arch",
                    "model_type=PNA needs Architecture.max_neighbours (the "
                    "degree histogram bound) — completion cannot derive "
                    "pna_deg without it",
                )
            )
            return None
        # Flat placeholder histogram: eval_shape only needs pna_deg's
        # PRESENCE — output shapes do not depend on its values.
        arch2["pna_deg"] = [1.0] * (int(mn) + 1)
    try:
        model = create_model_config(config=arch2, verbosity=0)
    except Exception as e:  # noqa: BLE001 — every builder error is a finding
        errors.append(
            ("bad-arch", f"Architecture cannot build a model: {e}")
        )
        return None

    example = make_example_batch(
        input_dim, output_dim, output_type, edge_dim=edge_dim,
        num_nodes=num_nodes,
    )
    # CSR batch contract (graphs/csr.py): the example batch carries the same
    # row pointers production collation emits — validate length, endpoints,
    # monotonicity, and agreement with the sorted receivers HERE, so a
    # collation/layout regression fails the config gate before any compile.
    from ..graphs.csr import validate_csr

    try:
        validate_csr(
            np.asarray(example.receivers), np.asarray(example.row_ptr),
            example.node_features.shape[0], what="receivers",
        )
        validate_csr(
            np.asarray(example.node_graph), np.asarray(example.graph_ptr),
            example.num_graphs_pad, what="node_graph",
        )
    except ValueError as e:
        errors.append(("shape-mismatch", str(e)))
        return round(time.perf_counter() - t0, 4)
    batch_sds = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype),
        example,
    )
    key_sds = jax.ShapeDtypeStruct((2,), np.uint32)

    def _trace_serving(batch, key):
        from ..train.trainer import _apply_model

        variables = model.init(
            {"params": key, "dropout": key}, batch, train=False
        )
        return _apply_model(
            model,
            variables["params"],
            variables.get("batch_stats", {}),
            batch,
            train=False,
        )

    # Precision policy (docs/PRECISION.md): with Training.precision="bf16"
    # the gate traces the MIXED-PRECISION step — bf16 compute casts plus the
    # in-jit loss-scale machine — so a dtype bug in a head/loss/optimizer
    # path fails here, not at step 1. The loss-scale state enters as
    # ShapeDtypeStructs (this check must still never allocate device arrays).
    bf16_policy = None
    if mode == "training" and training.get("precision") == "bf16":
        from ..precision.policy import LossScaleConfig

        try:
            bf16_policy = LossScaleConfig.from_config(
                training.get("loss_scale")
            )
        except (TypeError, ValueError):
            bf16_policy = None  # already a bad-precision structural finding

    def _trace_training(batch, key, ls=None):
        from ..train.trainer import _step_body, create_train_state
        from ..utils.optimizer import select_optimizer

        step_model = (
            model.clone(compute_dtype="bfloat16")
            if ls is not None and model.compute_dtype is None
            else model
        )
        variables = step_model.init(
            {"params": key, "dropout": key}, batch, train=False
        )
        # AdamW regardless of Training.optimizer: the shape contract is
        # optimizer-independent (module docstring).
        state = create_train_state(
            step_model, variables, select_optimizer("AdamW", 1e-3)
        )
        if ls is not None:
            state = state.replace(loss_scale=ls)
        new_state, metrics = _step_body(
            step_model,
            select_optimizer("AdamW", 1e-3),
            guard=True,
            loss_scaling=bf16_policy,
        )(state, batch, key)
        return metrics

    try:
        if mode in ("serving", "prediction"):  # forward-only surfaces
            out_shapes = jax.eval_shape(_trace_serving, batch_sds, key_sds)
            _check_output_shapes(
                out_shapes, output_dim, output_type, example, errors
            )
        else:
            if bf16_policy is not None:
                from ..precision.policy import LossScaleState

                ls_sds = LossScaleState(
                    scale=jax.ShapeDtypeStruct((), np.float32),
                    good_steps=jax.ShapeDtypeStruct((), np.int32),
                )
                metrics = jax.eval_shape(
                    _trace_training, batch_sds, key_sds, ls_sds
                )
            else:
                metrics = jax.eval_shape(_trace_training, batch_sds, key_sds)
            loss = metrics["loss"]
            if loss.shape != () or not np.issubdtype(loss.dtype, np.floating):
                errors.append(
                    (
                        "shape-mismatch",
                        f"guarded step loss has shape {loss.shape} dtype "
                        f"{loss.dtype}; expected a floating scalar",
                    )
                )
    except ConfigContractError:
        raise
    except Exception as e:  # noqa: BLE001 — trace errors ARE the findings
        errors.append(
            (
                "shape-mismatch",
                "eval_shape over model+loss+guarded step failed: "
                + str(e).splitlines()[0],
            )
        )
        return round(time.perf_counter() - t0, 4)
    return round(time.perf_counter() - t0, 4)


def _check_output_shapes(out_shapes, output_dim, output_type, example, errors):
    if len(out_shapes) != len(output_dim):
        errors.append(
            (
                "shape-mismatch",
                f"model emits {len(out_shapes)} head(s); config declares "
                f"{len(output_dim)}",
            )
        )
        return
    n_pad = example.node_features.shape[0]
    g_pad = example.num_graphs_pad
    for i, (shape, dim, kind) in enumerate(
        zip(out_shapes, output_dim, output_type)
    ):
        want_rows = g_pad if kind == "graph" else n_pad
        if tuple(shape.shape) != (want_rows, dim):
            errors.append(
                (
                    "shape-mismatch",
                    f"head {i} ({kind}): model emits {tuple(shape.shape)}, "
                    f"config declares ({want_rows}, {dim})",
                )
            )
