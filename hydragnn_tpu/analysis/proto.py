"""graftproto — static SPMD/barrier lockstep + incarnation-contract analyzer
for the distributed control plane (rule catalogue: rules.py, policy +
examples: docs/STATIC_ANALYSIS.md "graftproto").

graftlint covers in-jit discipline and graftrace covers thread/lock
discipline; this third leg covers the CROSS-RANK and CROSS-INCARNATION
layer the elastic/swap/flywheel state machines (PRs 13–17) introduced —
the protocols whose failure mode is not a wrong number but a mesh that
deadlocks on real multi-host hardware or a crash recovery that reads torn
state. Three rule families over the same parsed-module/call-graph
infrastructure (ProtoAnalyzer subclasses concurrency.Tracer, which
subclasses graftlint.Linter):

1. **Collective lockstep** (``collective-divergence``, never baselineable).
   XLA collectives (psum/pmean/ppermute/all_gather/...) are compiled into
   a fixed program; every rank must trace the IDENTICAL sequence. Inside
   traced code, any Python-level branch conditioned on a rank-identity
   name (rules.RANK_GUARD_NAMES), and any branch whose arms trace
   DIFFERENT transitive collective sequences while its condition depends
   on the function's own parameters, makes the sequence path-dependent.
   Closure/global names in a branch condition are trace-time constants
   (every rank closes over the same config) and stay clean — that is what
   keeps ``overlap.make_reduce``'s ``grad_sync`` dispatch legal.

2. **Barrier protocol** (``barrier-divergence``, ``barrier-under-lock``,
   ``leader-only-barrier``). Named rendezvous barrier sites are extracted
   per thread/lockstep root (graftrace's topology roots plus the
   ``run_workers`` lockstep segments — rules.LOCKSTEP_CALLABLE_BINDINGS,
   the runs-as-every-rank analog of THREAD_CALLABLE_BINDINGS). All members
   of one segment must reach the same barrier-name sequence
   (``barrier-divergence``); a barrier statically inside a ``with <lock>:``
   whose lock another root also acquires is a distributed convoy
   (``barrier-under-lock``); a barrier reachable only inside a
   rank-guarded branch strands the followers (``leader-only-barrier``).
   The rendezvous funnel methods themselves (rules.BARRIER_FUNNEL_METHODS)
   implement the protocol and are exempt.

3. **Incarnation contract** (``torn-state-hazard``, never baselineable).
   Control-plane state in rules.PERSISTENCE_STATE_MODULES must install
   through an atomic-rename funnel (rules.PERSISTENCE_CALLS — the
   tmp+fsync+os.replace shapes in checkpoint/io.py). A bare
   ``open(path, "w")`` write or ``shutil.copyfile`` in a function that
   never ``os.replace``s, or a two-file update mixing distinct persistence
   funnels without a single authoritative install site, leaves a window
   where a SIGKILL tears the recovered state. The static census of
   persistence-funnel call sites this pass produces is exactly what the
   runtime half (mck.py, ``python -m hydragnn_tpu.analysis modelcheck``)
   uses to auto-discover crash-injection points — the checker never
   hand-picks a kill site.

Suppressions use the shared grammar (``# graftproto: disable=rule(reason)``,
interchangeable with ``graftlint:``/``graftrace:``). ``collective-divergence``
and ``torn-state-hazard`` join the never-baselineable set (baseline.py):
a grandfathered rank-divergent collective deadlocks the first real
multi-host mesh; a grandfathered torn-state window corrupts every crash
recovery after it.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from . import rules as R
from .concurrency import Tracer
from .graftlint import (
    _FUNC_NODES,
    FuncInfo,
    ModuleInfo,
    Report,
    Violation,
    _dotted,
)

# Thread roots named "<prefix>-<digits>" are members of one lockstep segment
# (the convention run_workers/test fixtures use for per-rank threads).
_SEGMENT_MEMBER_RE = re.compile(r"^(?P<prefix>.+)-(?P<idx>\d+)$")

# open() modes that WRITE (a torn-state candidate in persistence modules).
_WRITE_MODES = ("w", "a", "x")

# shutil entry points that copy/move bytes non-atomically.
_COPY_CALLS = frozenset(
    {"shutil.copyfile", "shutil.copy", "shutil.copy2", "shutil.move"}
)

_ATOMIC_INSTALL_CALLS = frozenset({"os.replace", "os.rename"})


@dataclass
class PersistencePoint:
    """One static persistence-funnel call site — the model checker's
    injection-point census entry."""

    path: str
    qualname: str
    callee: str
    line: int

    @property
    def site_id(self) -> str:
        return f"{self.path}::{self.qualname}::{self.callee}"

    def as_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path,
            "qualname": self.qualname,
            "callee": self.callee,
            "line": self.line,
            "site_id": self.site_id,
        }


@dataclass
class ProtoReport(Report):
    """graftproto run result: graftlint's Report plus the lockstep topology
    and the persistence-point census the runtime half consumes."""

    lockstep_segments: Dict[str, List[str]] = field(default_factory=dict)
    barrier_sequences: Dict[str, List[str]] = field(default_factory=dict)
    persistence_points: List[Dict[str, Any]] = field(default_factory=list)
    collective_functions: List[str] = field(default_factory=list)


class ProtoAnalyzer(Tracer):
    """The graftproto pass. Reuses the linter's parsing/suppressions, the
    tracer's root discovery, call resolution, and lock model; adds the
    collective/barrier/persistence rule families."""

    def __init__(self, paths: Sequence[str], root: Optional[str] = None):
        super().__init__(paths, root=root)
        # segment name -> member FuncInfos (>= 2 members => sequence check)
        self.segments: Dict[str, List[FuncInfo]] = {}
        # root names whose functions execute as every rank of a segment
        self.lockstep_roots: Set[str] = set()
        self._fn_barrier_seq: Dict[int, Tuple[str, ...]] = {}
        self._fn_collective_seq: Dict[int, Tuple[str, ...]] = {}
        self.persistence_points: List[PersistencePoint] = []

    # ------------------------------------------------------------------ run
    def run_proto(self, check_suppressions: bool = True) -> ProtoReport:
        report = ProtoReport()
        self.load(report)
        self._index_classes()
        self._collect_guard_comments()
        self._infer_attr_types()
        self._mark_traced_roots()
        self._propagate_traced()
        self._discover_roots()
        self._discover_lockstep_roots()
        self._propagate_roots()
        self._build_lock_graph(report)
        self._collect_segments()
        self._check_collective_lockstep(report)
        self._check_barrier_protocol(report)
        self._check_incarnation_contract(report)
        if check_suppressions:
            self._check_proto_suppressions(report)
        report.lockstep_segments = {
            name: sorted(f.qualname for f in fns)
            for name, fns in sorted(self.segments.items())
        }
        report.barrier_sequences = {
            name: [
                list(self._barrier_seq(f.module, f)) for f in fns
            ][0] if fns else []
            for name, fns in sorted(self.segments.items())
        }
        report.persistence_points = [
            p.as_dict()
            for p in sorted(
                self.persistence_points, key=lambda p: (p.path, p.line)
            )
        ]
        report.collective_functions = sorted(
            {
                fn.qualname
                for mod in self.modules
                for fn in mod.functions
                if self._collective_seq(mod, fn)
            }
        )
        report.violations.sort(key=lambda v: (v.path, v.line, v.col))
        report.suppressed.sort(key=lambda v: (v.path, v.line, v.col))
        return report

    # -------------------------------------------------------- lockstep roots
    def _discover_lockstep_roots(self) -> None:
        """``run_workers(world, fn)`` executes ``fn`` as EVERY rank of one
        lockstep segment on f-string-named threads static analysis cannot
        read — rules.LOCKSTEP_CALLABLE_BINDINGS names the binding the way
        THREAD_CALLABLE_BINDINGS names the pipeline threads."""
        for mod in self.modules:
            for fn in mod.functions:
                for dotted, call in fn.calls:
                    tail = dotted.split(".")[-1]
                    binding = R.LOCKSTEP_CALLABLE_BINDINGS.get(tail)
                    if not binding:
                        continue
                    bound: List[ast.AST] = []
                    for i, arg in enumerate(call.args):
                        if i in binding:
                            bound.append(arg)
                    for kw in call.keywords:
                        if kw.arg in binding:
                            bound.append(kw.value)
                    for arg in bound:
                        tfn = self._resolve_callable_arg(mod, fn, arg)
                        if tfn is None:
                            continue
                        base = binding.get("fn") or next(iter(binding.values()))
                        # Segment identity is PER CALL SITE: two different
                        # run_workers() invocations are two independent
                        # rendezvous rounds, not peers of one segment.
                        seg = f"{base}@{fn.qualname}"
                        self._add_root(seg, tfn, mod.relpath)
                        self.lockstep_roots.add(seg)
                        members = self.segments.setdefault(seg, [])
                        if tfn not in members:
                            members.append(tfn)

    def _collect_segments(self) -> None:
        """Group constant-named thread roots ``<prefix>-<digits>`` into
        lockstep segments: per-rank threads spawned with literal names are
        peers of one rendezvous round and must trace the same barrier
        sequence."""
        by_qual: Dict[Tuple[str, str], FuncInfo] = {}
        for mod in self.modules:
            for fn in mod.functions:
                by_qual[(mod.relpath, fn.qualname)] = fn
        groups: Dict[str, List[Tuple[str, FuncInfo]]] = {}
        for root, wheres in self.roots_found.items():
            m = _SEGMENT_MEMBER_RE.match(root)
            if not m:
                continue
            for where in wheres:
                relpath, _, qual = where.partition("::")
                fn = by_qual.get((relpath, qual))
                if fn is not None:
                    groups.setdefault(m.group("prefix"), []).append(
                        (root, fn)
                    )
        for prefix, members in groups.items():
            fns: List[FuncInfo] = []
            for root, fn in members:
                if fn not in fns:
                    fns.append(fn)
            if len(members) >= 2:
                seg = self.segments.setdefault(prefix, [])
                for fn in fns:
                    if fn not in seg:
                        seg.append(fn)
                self.lockstep_roots.update(r for r, _ in members)

    def _is_lockstep_fn(self, fn: FuncInfo) -> bool:
        return bool(fn.roots & self.lockstep_roots)

    # ---------------------------------------------------- ordered traversal
    @classmethod
    def _ordered_own(cls, node: ast.AST):
        """Depth-first, source-order traversal that does not descend into
        nested function definitions (their sequences are accounted through
        the call graph when they are actually called)."""
        for child in ast.iter_child_nodes(node):
            yield child
            if not isinstance(child, _FUNC_NODES):
                yield from cls._ordered_own(child)

    # --------------------------------------------------- collective lockstep
    @staticmethod
    def _collective_tail(canon: str, dotted: str) -> Optional[str]:
        """The collective op name if this dotted call is one (``lax.psum``,
        ``jax.lax.ppermute``, bare ``psum`` through a from-import)."""
        for probe in (canon, dotted):
            if not probe:
                continue
            parts = probe.split(".")
            if parts[-1] in R.COLLECTIVE_CALLS:
                prefix = parts[:-1]
                if not prefix or prefix[-1] in ("lax", "jax") or (
                    len(prefix) >= 2 and prefix[-2:] == ["jax", "lax"]
                ):
                    return parts[-1]
        return None

    @staticmethod
    def _call_axis_name(call: ast.Call) -> str:
        """The axis_name literal, when visible — part of the sequence
        element so ``psum('data')`` != ``psum('graph')``."""
        cands: List[ast.AST] = list(call.args[1:2])
        for kw in call.keywords:
            if kw.arg == "axis_name":
                cands.append(kw.value)
        for c in cands:
            if isinstance(c, ast.Constant) and isinstance(c.value, str):
                return c.value
        return "?"

    def _collective_seq(
        self,
        mod: ModuleInfo,
        fn: FuncInfo,
        _stack: Optional[Set[int]] = None,
    ) -> Tuple[str, ...]:
        """Transitive source-order collective sequence of ``fn`` (cycle
        guarded, memoized): its own collective calls plus those of every
        statically-resolvable callee."""
        cached = self._fn_collective_seq.get(id(fn))
        if cached is not None:
            return cached
        stack = _stack or set()
        if id(fn) in stack:
            return ()
        stack = stack | {id(fn)}
        seq = tuple(self._seq_of_body(mod, fn, fn.node, stack, "collective"))
        if _stack is None:
            self._fn_collective_seq[id(fn)] = seq
        return seq

    def _barrier_seq(
        self,
        mod: ModuleInfo,
        fn: FuncInfo,
        _stack: Optional[Set[int]] = None,
    ) -> Tuple[str, ...]:
        """Transitive source-order rendezvous-round sequence of ``fn``:
        named barriers plus tagged exchange/broadcast/allgather rounds."""
        cached = self._fn_barrier_seq.get(id(fn))
        if cached is not None:
            return cached
        stack = _stack or set()
        if id(fn) in stack:
            return ()
        stack = stack | {id(fn)}
        seq = tuple(self._seq_of_body(mod, fn, fn.node, stack, "barrier"))
        if _stack is None:
            self._fn_barrier_seq[id(fn)] = seq
        return seq

    _BARRIER_TAILS = ("barrier", "exchange", "broadcast", "allgather")

    def _is_funnel_fn(self, fn: FuncInfo) -> bool:
        return (fn.class_name, fn.name) in R.BARRIER_FUNNEL_METHODS

    @classmethod
    def _barrier_site_name(cls, call: ast.Call, tail: str) -> Optional[str]:
        """The sequence element for a rendezvous-round call site, or None
        when the call is not one (an attribute named ``exchange`` on an
        arbitrary object without a tag is ignored — only ``barrier`` is
        unambiguous without one)."""
        name = None
        kwname = "name" if tail == "barrier" else "tag"
        for kw in call.keywords:
            if kw.arg == kwname and isinstance(kw.value, ast.Constant):
                name = str(kw.value.value)
        if name is None and tail == "barrier":
            for arg in call.args:
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    name = arg.value
                    break
            if name is None:
                name = "barrier" if not call.args else "<dynamic>"
        if name is None and tail != "barrier":
            return None
        return f"{tail}:{name}"

    def _seq_of_body(
        self,
        mod: ModuleInfo,
        fn: FuncInfo,
        node: ast.AST,
        stack: Set[int],
        kind: str,
    ) -> List[str]:
        out: List[str] = []
        for sub in self._ordered_own(node):
            if not isinstance(sub, ast.Call):
                continue
            dotted = _dotted(sub.func) or ""
            if kind == "collective":
                canon = mod.canonical(dotted) or ""
                tail = self._collective_tail(canon, dotted)
                if tail:
                    out.append(f"{tail}:{self._call_axis_name(sub)}")
                    continue
            else:
                if isinstance(sub.func, ast.Attribute) and (
                    sub.func.attr in self._BARRIER_TAILS
                ):
                    el = self._barrier_site_name(sub, sub.func.attr)
                    if el is not None:
                        out.append(el)
                        continue
            if dotted:
                target = self._resolve_call_ext(mod, fn, dotted)
                if target is not None and not self._is_funnel_fn(target):
                    if kind == "collective":
                        out.extend(
                            self._collective_seq(target.module, target, stack)
                        )
                    else:
                        out.extend(
                            self._barrier_seq(target.module, target, stack)
                        )
        return out

    @staticmethod
    def _test_names(test: ast.AST) -> Set[str]:
        """Plain names and attribute tails referenced by a branch
        condition."""
        names: Set[str] = set()
        for node in ast.walk(test):
            if isinstance(node, ast.Name):
                names.add(node.id)
            elif isinstance(node, ast.Attribute):
                names.add(node.attr)
        return names

    @staticmethod
    def _fn_params(fn: FuncInfo) -> Set[str]:
        args = getattr(fn.node, "args", None)
        if args is None:
            return set()
        out = {a.arg for a in list(args.args) + list(args.kwonlyargs)}
        if args.vararg:
            out.add(args.vararg.arg)
        if args.kwarg:
            out.add(args.kwarg.arg)
        return out

    @staticmethod
    def _arm_terminates(body: List[ast.stmt]) -> bool:
        return any(
            isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue))
            for s in body
        )

    def _check_collective_lockstep(self, report: ProtoReport) -> None:
        for mod in self.modules:
            for fn in mod.functions:
                traced = fn.traced
                lockstep = self._is_lockstep_fn(fn)
                if not traced and not lockstep:
                    continue
                if self._is_funnel_fn(fn):
                    continue
                params = self._fn_params(fn)
                for node in self._ordered_own(fn.node):
                    if isinstance(node, (ast.If, ast.IfExp, ast.While)):
                        self._check_branch(
                            report, mod, fn, node, traced, params
                        )

    def _check_branch(
        self,
        report: ProtoReport,
        mod: ModuleInfo,
        fn: FuncInfo,
        node: ast.AST,
        traced: bool,
        params: Set[str],
    ) -> None:
        names = self._test_names(node.test)  # type: ignore[attr-defined]
        rank_guarded = bool(names & R.RANK_GUARD_NAMES)
        if traced and rank_guarded:
            self._emit(
                report,
                mod,
                "collective-divergence",
                node,
                "branch conditioned on rank identity "
                f"({sorted(names & R.RANK_GUARD_NAMES)}) inside traced "
                "code — ranks trace different programs and the mesh's "
                "collective sequence diverges",
                fn.qualname,
            )
            return
        if traced:
            # A Python branch that EXECUTES inside traced code is by
            # construction on a trace-time-static value (branching on a
            # tracer raises TracerBoolConversionError at trace time, which
            # jit itself catches), and a non-rank static — axis_name, a mode
            # flag, a ladder rung — is identical on every rank of the single
            # program. Only rank-derived conditions (handled above) can make
            # the traced collective sequence diverge.
            return
        if isinstance(node, ast.While):
            return
        # Path-dependent collective sequence: the arms trace different
        # collectives and the condition is NOT a trace-time constant
        # (it depends on the function's own parameters or rank names;
        # closure/global config names are the same on every rank).
        if isinstance(node, ast.IfExp):
            arm_a = self._seq_of_expr(mod, fn, node.body)
            arm_b = self._seq_of_expr(mod, fn, node.orelse)
            diverges = arm_a != arm_b
        else:
            arm_a = tuple(
                s
                for stmt in node.body
                for s in self._seq_of_body(
                    mod, fn, stmt, {id(fn)}, "collective"
                )
            )
            arm_b = tuple(
                s
                for stmt in node.orelse
                for s in self._seq_of_body(
                    mod, fn, stmt, {id(fn)}, "collective"
                )
            )
            diverges = arm_a != arm_b
            if not diverges and (
                self._arm_terminates(node.body)
                != self._arm_terminates(node.orelse or [])
            ):
                # An early return/raise in one arm makes everything AFTER
                # the branch part of the other path only.
                rest = self._collectives_after(mod, fn, node)
                diverges = bool(rest)
        if not diverges:
            return
        dependent = bool(names & params) or rank_guarded
        if not dependent:
            return
        scope = "traced" if traced else "lockstep-segment"
        self._emit(
            report,
            mod,
            "collective-divergence",
            node,
            f"branch arms trace different collective sequences "
            f"({list(arm_a) or 'none'} vs {list(arm_b) or 'none'}) and the "
            f"condition depends on {sorted(names & (params | R.RANK_GUARD_NAMES))} "
            f"— a non-constant in {scope} code makes the mesh's collective "
            "sequence path-dependent",
            fn.qualname,
        )

    def _seq_of_expr(
        self, mod: ModuleInfo, fn: FuncInfo, expr: ast.AST
    ) -> Tuple[str, ...]:
        return tuple(self._seq_of_body(mod, fn, expr, {id(fn)}, "collective"))

    def _collectives_after(
        self, mod: ModuleInfo, fn: FuncInfo, branch: ast.AST
    ) -> Tuple[str, ...]:
        """Collective sequence of the statements following ``branch`` in its
        enclosing body (what an early-returning arm skips)."""
        out: List[str] = []

        def scan(node: ast.AST) -> bool:
            for name in ("body", "orelse", "finalbody"):
                stmts = getattr(node, name, None)
                if not isinstance(stmts, list):
                    continue
                for i, stmt in enumerate(stmts):
                    if stmt is branch:
                        for later in stmts[i + 1:]:
                            out.extend(
                                self._seq_of_body(
                                    mod, fn, later, {id(fn)}, "collective"
                                )
                            )
                        return True
                    if not isinstance(stmt, _FUNC_NODES) and scan(stmt):
                        return True
            return False

        scan(fn.node)
        return tuple(out)

    # ------------------------------------------------------ barrier protocol
    def _check_barrier_protocol(self, report: ProtoReport) -> None:
        self._check_barrier_divergence(report)
        for mod in self.modules:
            for fn in mod.functions:
                if self._is_funnel_fn(fn):
                    continue
                self._check_leader_only(report, mod, fn)
                self._check_barrier_under_lock(report, mod, fn)

    def _check_barrier_divergence(self, report: ProtoReport) -> None:
        for name, members in sorted(self.segments.items()):
            if len(members) < 2:
                continue
            seqs = [
                (fn, self._barrier_seq(fn.module, fn)) for fn in members
            ]
            base_fn, base = seqs[0]
            for fn, seq in seqs[1:]:
                if seq != base:
                    self._emit(
                        report,
                        fn.module,
                        "barrier-divergence",
                        fn.node,
                        f"lockstep segment {name!r}: {fn.qualname} reaches "
                        f"barrier sequence {list(seq)} but peer "
                        f"{base_fn.qualname} reaches {list(base)} — the "
                        "rendezvous round can never complete",
                        fn.qualname,
                    )

    def _barrier_calls_under(
        self, mod: ModuleInfo, fn: FuncInfo, node: ast.AST
    ) -> List[Tuple[ast.Call, str]]:
        """(call node, element) pairs for every rendezvous round reachable
        from ``node``'s subtree — direct sites plus through-calls."""
        out: List[Tuple[ast.Call, str]] = []
        nodes = [node] if isinstance(node, ast.Call) else []
        nodes += [
            n for n in self._ordered_own(node) if isinstance(n, ast.Call)
        ]
        for call in nodes:
            if isinstance(call.func, ast.Attribute) and (
                call.func.attr in self._BARRIER_TAILS
            ):
                el = self._barrier_site_name(call, call.func.attr)
                if el is not None:
                    out.append((call, el))
                    continue
            dotted = _dotted(call.func) or ""
            if dotted:
                target = self._resolve_call_ext(mod, fn, dotted)
                if target is not None and not self._is_funnel_fn(target):
                    seq = self._barrier_seq(target.module, target)
                    if seq:
                        out.append(
                            (call, f"{dotted}() -> {seq[0]}")
                        )
        return out

    def _check_leader_only(
        self, report: ProtoReport, mod: ModuleInfo, fn: FuncInfo
    ) -> None:
        for node in self._ordered_own(fn.node):
            if not isinstance(node, ast.If):
                continue
            names = self._test_names(node.test)
            guards = names & R.RANK_GUARD_NAMES
            if not guards:
                continue
            for arm in (node.body, node.orelse):
                for stmt in arm:
                    for call, el in self._barrier_calls_under(
                        mod, fn, stmt
                    ):
                        self._emit(
                            report,
                            mod,
                            "leader-only-barrier",
                            call,
                            f"rendezvous round {el!r} inside a branch "
                            f"guarded by rank identity ({sorted(guards)}) "
                            "— the other ranks never arrive and the round "
                            "blocks until timeout",
                            fn.qualname,
                        )

    def _check_barrier_under_lock(
        self, report: ProtoReport, mod: ModuleInfo, fn: FuncInfo
    ) -> None:
        held_map = self._held_locks_map(mod, fn)
        lock_roots = self._lock_acquirer_roots()
        for call, el in self._barrier_calls_under(mod, fn, fn.node):
            held = held_map.get(id(call), frozenset())
            if not held:
                continue
            for lock in sorted(held):
                other = lock_roots.get(lock, set()) - fn.roots
                if other:
                    self._emit(
                        report,
                        mod,
                        "barrier-under-lock",
                        call,
                        f"rendezvous round {el!r} while holding "
                        f"{lock.split('::')[-1]}, which thread root(s) "
                        f"{sorted(other)} also acquire — peers blocked on "
                        "the lock never reach the barrier (distributed "
                        "deadlock)",
                        fn.qualname,
                    )
                    break

    def _lock_acquirer_roots(self) -> Dict[str, Set[str]]:
        cached = getattr(self, "_lock_roots_cache", None)
        if cached is not None:
            return cached
        out: Dict[str, Set[str]] = {}
        for mod in self.modules:
            for fn in mod.functions:
                for lock in self._fn_acquires.get(id(fn), ()):
                    out.setdefault(lock, set()).update(fn.roots)
        self._lock_roots_cache = out
        return out

    # --------------------------------------------------- incarnation contract
    def _check_incarnation_contract(self, report: ProtoReport) -> None:
        for mod in self.modules:
            in_scope = any(
                mod.relpath.endswith(m) for m in R.PERSISTENCE_STATE_MODULES
            )
            for fn in mod.functions:
                if in_scope:
                    self._census_fn(mod, fn)
                    if fn.name not in R.PERSISTENCE_FUNNEL_FUNCTIONS:
                        self._check_torn_state(report, mod, fn)

    def _census_fn(self, mod: ModuleInfo, fn: FuncInfo) -> None:
        for dotted, call in fn.calls:
            tail = dotted.split(".")[-1]
            if tail in R.PERSISTENCE_CALLS:
                self.persistence_points.append(
                    PersistencePoint(
                        path=mod.relpath,
                        qualname=fn.qualname,
                        callee=tail,
                        line=getattr(call, "lineno", fn.line),
                    )
                )

    def _check_torn_state(
        self, report: ProtoReport, mod: ModuleInfo, fn: FuncInfo
    ) -> None:
        has_atomic_install = False
        raw_writes: List[Tuple[ast.Call, str]] = []
        funnel_calls: List[Tuple[ast.Call, str, str]] = []
        for node in self._ordered_own(fn.node):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func) or ""
            canon = mod.canonical(dotted) or dotted
            if canon in _ATOMIC_INSTALL_CALLS:
                has_atomic_install = True
                continue
            if canon in _COPY_CALLS:
                raw_writes.append((node, canon))
                continue
            if canon == "open" or dotted == "open":
                mode = None
                if len(node.args) >= 2 and isinstance(
                    node.args[1], ast.Constant
                ):
                    mode = node.args[1].value
                for kw in node.keywords:
                    if kw.arg == "mode" and isinstance(
                        kw.value, ast.Constant
                    ):
                        mode = kw.value.value
                if isinstance(mode, str) and any(
                    m in mode for m in _WRITE_MODES
                ):
                    raw_writes.append((node, f"open(..., {mode!r})"))
                continue
            tail = dotted.split(".")[-1]
            if tail in R.PERSISTENCE_CALLS:
                target = node.args[0] if node.args else None
                target_desc = (
                    _dotted(target)
                    or (
                        repr(target.value)
                        if isinstance(target, ast.Constant)
                        else ast.dump(target)[:60]
                    )
                    if target is not None
                    else "?"
                )
                funnel_calls.append((node, tail, target_desc))
        if not has_atomic_install:
            for node, desc in raw_writes:
                self._emit(
                    report,
                    mod,
                    "torn-state-hazard",
                    node,
                    f"{desc} writes control-plane state without an atomic "
                    "rename — a crash mid-write leaves a torn file the "
                    "next incarnation reads; route through "
                    "checkpoint.io's tmp+fsync+os.replace funnels",
                    fn.qualname,
                )
        distinct = {(callee, tgt) for _, callee, tgt in funnel_calls}
        if len(distinct) >= 2:
            callees = {c for c, _ in distinct}
            if len(callees) >= 2 or len({t for _, t in distinct}) >= 2:
                node = funnel_calls[-1][0]
                self._emit(
                    report,
                    mod,
                    "torn-state-hazard",
                    node,
                    "two-file state update in one function "
                    f"({sorted('%s(%s)' % d for d in distinct)}) without a "
                    "single authoritative install site — a crash between "
                    "the installs tears the pair; make one file the "
                    "authority (installed last) or merge the update",
                    fn.qualname,
                )

    # ------------------------------------------------------ suppression meta
    def _check_proto_suppressions(self, report: ProtoReport) -> None:
        """Reason-less suppressions for the PROTO rules only (the lint pass
        owns the check for its rules; the combined CLI run disables this
        half to avoid double reports)."""
        for mod in self.modules:
            for line, (rule, reason) in sorted(mod.suppressions.items()):
                if rule not in R.PROTO_RULES:
                    continue
                if not reason:
                    report.violations.append(
                        Violation(
                            rule="suppression-without-reason",
                            path=mod.relpath,
                            line=line,
                            col=0,
                            message=(
                                f"disable={rule} needs a justification: "
                                f"# graftproto: disable={rule}(why this is "
                                "safe)"
                            ),
                            qualname="<module>",
                        )
                    )


def proto_paths(
    paths: Sequence[str],
    root: Optional[str] = None,
    check_suppressions: bool = True,
) -> ProtoReport:
    """Run graftproto over files/directories; returns the ProtoReport
    (violations exclude properly-suppressed ones, which land in
    ``report.suppressed``)."""
    return ProtoAnalyzer(paths, root=root).run_proto(
        check_suppressions=check_suppressions
    )
