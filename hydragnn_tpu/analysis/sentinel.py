"""Recompile sentinel: process-wide XLA-compile accounting + the
``no_recompile()`` context manager.

This generalizes the serve engine's explicit compiled-executable cache
accounting (serve/metrics.py counts hits/misses because the engine owns its
cache) to ANY code path: JAX emits exactly one
``/jax/core/compile/backend_compile_duration`` monitoring event per real XLA
compilation — jit cache misses and explicit ``.lower().compile()`` both fire
it, cache hits and executions do not (verified against this container's
jax). One listener increments a process-wide counter; ``no_recompile()``
snapshots it around a region that is contractually post-warmup:

    with no_recompile(label="steady epochs") as watch:
        for _ in range(epochs):
            driver.train_epoch(loader)
    # watch.count == 0, or RecompileError listing label + count

Used by the trainer's device-cached replay epochs (warn by default — a
production run must not die on an unexpected compile, but the operator must
see it), by bench.py's steady measurement windows and the serving load
benchmark (action="raise" — a recompile there invalidates the measurement),
and by tests locking the zero-recompile-after-warmup contracts.

The listener counts compiles from ALL threads — deliberate: the serve
engine compiles on its dispatch thread, and those are exactly the compiles a
post-warmup serving assertion must see.
"""

from __future__ import annotations

import contextlib
import threading
import warnings
from dataclasses import dataclass

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_lock = threading.Lock()
_state = {"installed": False, "compiles": 0}


class RecompileError(RuntimeError):
    """A region declared recompile-free compiled anyway."""


def _on_event(name: str, duration: float, **kwargs) -> None:
    if name == _COMPILE_EVENT:
        with _lock:
            _state["compiles"] += 1


def _ensure_listener() -> None:
    with _lock:
        if _state["installed"]:
            return
        _state["installed"] = True
    import jax

    jax.monitoring.register_event_duration_secs_listener(_on_event)


def compile_count() -> int:
    """Total XLA compilations observed in this process (since the first
    sentinel use — call early if absolute counts matter)."""
    _ensure_listener()
    with _lock:
        return _state["compiles"]


@dataclass
class RecompileWatch:
    label: str
    start: int
    count: int = 0

    @property
    def compiles(self) -> int:  # alias; reads naturally at call sites
        return self.count


@contextlib.contextmanager
def no_recompile(allow: int = 0, action: str = "raise", label: str = ""):
    """Assert the wrapped region performs at most ``allow`` XLA compiles.

    action: "raise" → RecompileError; "warn" → warnings.warn (production
    paths — visible, never fatal); "count" → record only (the yielded
    ``RecompileWatch.count`` carries the tally either way).
    """
    if action not in ("raise", "warn", "count"):
        raise ValueError(f"unknown no_recompile action {action!r}")
    _ensure_listener()
    watch = RecompileWatch(label=label, start=compile_count())
    try:
        yield watch
    finally:
        watch.count = compile_count() - watch.start
    if watch.count > allow:
        msg = (
            f"no_recompile({label or 'region'}): {watch.count} XLA "
            f"compilation(s) in a region declared recompile-free "
            f"(allow={allow}) — a warmup is incomplete or a static shape / "
            "hashable-arg contract broke"
        )
        if action == "raise":
            raise RecompileError(msg)
        if action == "warn":
            warnings.warn(msg, RuntimeWarning, stacklevel=3)
