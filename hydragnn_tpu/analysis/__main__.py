"""CLI for the static-analysis layer.

    python -m hydragnn_tpu.analysis [lint] [paths...] [--json]
        graftlint + graftrace (default: the hydragnn_tpu package). Exit 0
        iff no violation beyond the committed baseline; --update-baseline
        rewrites it. --no-trace restores the lint-only run.

    python -m hydragnn_tpu.analysis trace [paths...] [--json]
        graftrace alone: thread topology, lock discipline, lock-order
        graph. Exit 0 iff clean vs baseline (unguarded-shared-write is
        never baselineable).

    python -m hydragnn_tpu.analysis check-config <config.json>
        [--mode training|serving] [--bucket-ladder NxE,NxE] [--json]
        Static contract check; exit 0 iff the config passes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    DEFAULT_BASELINE_PATH,
    check_config,
    lint_paths,
    load_baseline,
    new_violations,
    save_baseline,
    trace_paths,
)
from . import rules as R
from .contracts import ConfigContractError

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_main(args) -> int:
    paths = args.paths or [_PACKAGE_DIR]
    root = os.path.dirname(_PACKAGE_DIR)
    report = lint_paths(paths, root=root)
    trace = None
    if not getattr(args, "no_trace", False):
        # The lint pass already meta-checks every suppression (both
        # grammars share rules.RULES), so the trace half skips its own
        # suppression check to avoid double reports.
        trace = trace_paths(paths, root=root, check_suppressions=False)
        report.violations.extend(trace.violations)
        report.suppressed.extend(trace.suppressed)
        report.violations.sort(key=lambda v: (v.path, v.line, v.col))
        report.suppressed.sort(key=lambda v: (v.path, v.line, v.col))
    baseline = load_baseline(args.baseline)
    fresh = new_violations(report, baseline)
    if args.update_baseline:
        # A lint-only rewrite must not clobber the trace pass's entries in
        # the shared file (the combined run rewrites everything); entries
        # this report re-emits are dropped so counts don't inflate.
        report_keys = {v.key for v in report.violations}
        preserve = (
            {
                k: n
                for k, n in baseline.items()
                if k.rsplit("::", 1)[-1] in R.CONCURRENCY_RULES
                and k not in report_keys
            }
            if trace is None
            else None
        )
        entries = save_baseline(report, args.baseline, preserve=preserve)
        print(f"baseline updated: {len(entries)} entrie(s) at {args.baseline}")
        return 0
    if args.json:
        doc = {
            "files": report.files,
            "traced_functions": report.traced_functions,
            "rule_counts": report.counts(),
            "violations": [v.format() for v in report.violations],
            "new_violations": [v.format() for v in fresh],
            "suppressed": [v.format() for v in report.suppressed],
            "baseline_entries": sum(baseline.values()),
            "ok": not fresh,
        }
        if trace is not None:
            doc["trace"] = _trace_summary(trace)
        print(json.dumps(doc))
    else:
        for v in report.violations:
            marker = "" if v.key in baseline else " [NEW]"
            print(v.format() + marker)
        for v in report.suppressed:
            print(v.format() + f" — reason: {v.reason}")
        print(
            f"graftlint: {report.files} file(s), "
            f"{report.traced_functions} traced function(s), "
            f"{len(report.violations)} violation(s) "
            f"({len(fresh)} new vs baseline), "
            f"{len(report.suppressed)} suppressed"
        )
        if trace is not None:
            print(
                f"graftrace: {len(trace.thread_roots)} thread root(s), "
                f"{len(trace.shared_attrs)} shared attribute(s), "
                f"{trace.declared_attrs} guard declaration(s), "
                f"{len(trace.lock_edges)} lock-order edge(s), "
                f"{len(trace.lock_cycles)} cycle(s)"
            )
    return 1 if fresh else 0


def _trace_summary(report) -> dict:
    return {
        "thread_roots": report.thread_roots,
        "shared_attrs": report.shared_attrs,
        "declared_attrs": report.declared_attrs,
        "lock_nodes": report.lock_nodes,
        "lock_edges": [f"{a} -> {b}" for a, b in report.lock_edges],
        "lock_cycles": report.lock_cycles,
    }


def _trace_main(args) -> int:
    paths = args.paths or [_PACKAGE_DIR]
    root = os.path.dirname(_PACKAGE_DIR)
    report = trace_paths(paths, root=root)
    baseline = load_baseline(args.baseline)
    fresh = new_violations(report, baseline)
    if args.update_baseline:
        # Keep the lint pass's entries: this rewrite only owns the
        # concurrency rules' rows in the shared baseline file. Entries this
        # report RE-EMITS are dropped from the preserved set (a bare
        # graftrace-rule suppression is flagged by both grammars under the
        # same key — preserving AND re-adding would inflate its count).
        report_keys = {v.key for v in report.violations}
        preserve = {
            k: n
            for k, n in baseline.items()
            if k.rsplit("::", 1)[-1] not in R.CONCURRENCY_RULES
            and k not in report_keys
        }
        entries = save_baseline(report, args.baseline, preserve=preserve)
        print(f"baseline updated: {len(entries)} entrie(s) at {args.baseline}")
        return 0
    if args.json:
        doc = {
            "files": report.files,
            "rule_counts": report.counts(),
            "violations": [v.format() for v in report.violations],
            "new_violations": [v.format() for v in fresh],
            "suppressed": [v.format() for v in report.suppressed],
            "ok": not fresh,
        }
        doc.update(_trace_summary(report))
        print(json.dumps(doc))
    else:
        for v in report.violations:
            marker = "" if v.key in baseline else " [NEW]"
            print(v.format() + marker)
        for v in report.suppressed:
            print(v.format() + f" — reason: {v.reason}")
        roots = ", ".join(report.thread_roots) or "<none>"
        print(
            f"graftrace: {report.files} file(s); thread roots: {roots}; "
            f"{len(report.shared_attrs)} shared attribute(s), "
            f"{report.declared_attrs} guard declaration(s), "
            f"{len(report.lock_edges)} lock-order edge(s), "
            f"{len(report.lock_cycles)} cycle(s), "
            f"{len(report.violations)} violation(s) ({len(fresh)} new), "
            f"{len(report.suppressed)} suppressed"
        )
    return 1 if fresh else 0


def _check_config_main(args) -> int:
    ladder = None
    if args.bucket_ladder:
        ladder = []
        for part in filter(None, (p.strip() for p in args.bucket_ladder.split(","))):
            try:
                n, e = part.split("x")
                ladder.append((int(n), int(e)))
            except ValueError:
                # Malformed rung: hand the raw string to the checker, which
                # reports it as a one-line oob-bucket finding instead of a
                # parse traceback here.
                ladder.append(part)
    try:
        report = check_config(
            args.config, mode=args.mode, bucket_ladder=ladder, strict=False
        )
    except ConfigContractError as e:  # malformed beyond reporting
        print(f"check-config: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report))
    else:
        for err in report["errors"]:
            print(f"check-config: [{err['code']}] {err['message']}")
        for s in report["skipped"]:
            print(f"check-config: skipped — {s}")
        status = "OK" if report["ok"] else "FAILED"
        extra = (
            f" (eval_shape {report['eval_shape_s']}s)"
            if report.get("eval_shape_s") is not None
            else ""
        )
        print(f"check-config: {status} [{report['mode']}]{extra}")
    return 0 if report["ok"] else 1


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.analysis",
        description="graftlint + static config contract checker",
    )
    sub = ap.add_subparsers(dest="cmd")
    lint = sub.add_parser(
        "lint", help="run graftlint + graftrace (the default command)"
    )
    lint.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    lint.add_argument("--json", action="store_true")
    lint.add_argument("--baseline", default=DEFAULT_BASELINE_PATH)
    lint.add_argument("--update-baseline", action="store_true")
    lint.add_argument(
        "--no-trace",
        action="store_true",
        help="lint only (skip the graftrace concurrency pass)",
    )
    tr = sub.add_parser(
        "trace", help="graftrace: thread topology + lock discipline"
    )
    tr.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    tr.add_argument("--json", action="store_true")
    tr.add_argument("--baseline", default=DEFAULT_BASELINE_PATH)
    tr.add_argument("--update-baseline", action="store_true")
    cc = sub.add_parser("check-config", help="static config contract check")
    cc.add_argument("config")
    cc.add_argument(
        "--mode",
        choices=("training", "prediction", "serving"),
        default="training",
    )
    cc.add_argument(
        "--bucket-ladder",
        default="",
        help='serving bucket shapes "NxE,NxE" to validate against the config',
    )
    cc.add_argument("--json", action="store_true")
    return ap


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Default subcommand: bare invocation (or paths/flags only) means lint.
    if not argv or argv[0] not in ("lint", "trace", "check-config", "-h", "--help"):
        argv = ["lint"] + argv
    args = build_parser().parse_args(argv)
    if args.cmd == "check-config":
        return _check_config_main(args)
    if args.cmd == "trace":
        return _trace_main(args)
    return _lint_main(args)


if __name__ == "__main__":
    sys.exit(main())
