"""CLI for the static-analysis layer.

    python -m hydragnn_tpu.analysis [lint] [paths...] [--json]
        graftlint + graftrace (default: the hydragnn_tpu package). Exit 0
        iff no violation beyond the committed baseline; --update-baseline
        rewrites it. --no-trace restores the lint-only run.

    python -m hydragnn_tpu.analysis trace [paths...] [--json]
        graftrace alone: thread topology, lock discipline, lock-order
        graph. Exit 0 iff clean vs baseline (unguarded-shared-write is
        never baselineable).

    python -m hydragnn_tpu.analysis check-config <config.json>
        [--mode training|serving] [--bucket-ladder NxE,NxE] [--json]
        Static contract check; exit 0 iff the config passes.

    python -m hydragnn_tpu.analysis proto [paths...] [--json]
        graftproto alone: collective-lockstep, barrier-protocol and
        incarnation-contract rules over the distributed control plane.
        Exit 0 iff clean vs baseline (collective-divergence and
        torn-state-hazard are never baselineable).

    python -m hydragnn_tpu.analysis modelcheck [--smoke] [--seed N]
        [--scenario NAME ...] [--json]
        Crash-consistency model checker: inject a crash at every
        auto-discovered persistence point across the elastic/swap/flywheel
        state machines and assert the recovery invariants. Exit 0 iff all
        injections recover clean.

    python -m hydragnn_tpu.analysis suppressions [paths...] [--json]
        Audit every inline graftlint:/graftrace:/graftproto: disable
        (file:line, rule, reason). Exit 0 iff none is reason-less.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (
    DEFAULT_BASELINE_PATH,
    check_config,
    lint_paths,
    load_baseline,
    model_check,
    new_violations,
    proto_paths,
    save_baseline,
    trace_paths,
)
from . import rules as R
from .contracts import ConfigContractError

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lint_main(args) -> int:
    paths = args.paths or [_PACKAGE_DIR]
    root = os.path.dirname(_PACKAGE_DIR)
    report = lint_paths(paths, root=root)
    trace = None
    if not getattr(args, "no_trace", False):
        # The lint pass already meta-checks every suppression (both
        # grammars share rules.RULES), so the trace half skips its own
        # suppression check to avoid double reports.
        trace = trace_paths(paths, root=root, check_suppressions=False)
        report.violations.extend(trace.violations)
        report.suppressed.extend(trace.suppressed)
        report.violations.sort(key=lambda v: (v.path, v.line, v.col))
        report.suppressed.sort(key=lambda v: (v.path, v.line, v.col))
    baseline = load_baseline(args.baseline)
    fresh = new_violations(report, baseline)
    if args.update_baseline:
        # A lint-only rewrite must not clobber the trace OR proto passes'
        # entries in the shared file (the combined run still only covers
        # lint+trace, so proto rows are always preserved); entries this
        # report re-emits are dropped so counts don't inflate.
        report_keys = {v.key for v in report.violations}
        other_rules = (
            R.PROTO_RULES
            if trace is not None
            else (R.CONCURRENCY_RULES | R.PROTO_RULES)
        )
        preserve = {
            k: n
            for k, n in baseline.items()
            if k.rsplit("::", 1)[-1] in other_rules and k not in report_keys
        }
        entries = save_baseline(report, args.baseline, preserve=preserve)
        print(f"baseline updated: {len(entries)} entrie(s) at {args.baseline}")
        return 0
    if args.json:
        doc = {
            "files": report.files,
            "traced_functions": report.traced_functions,
            "rule_counts": report.counts(),
            "violations": [v.format() for v in report.violations],
            "new_violations": [v.format() for v in fresh],
            "suppressed": [v.format() for v in report.suppressed],
            "baseline_entries": sum(baseline.values()),
            "ok": not fresh,
        }
        if trace is not None:
            doc["trace"] = _trace_summary(trace)
        print(json.dumps(doc))
    else:
        for v in report.violations:
            marker = "" if v.key in baseline else " [NEW]"
            print(v.format() + marker)
        for v in report.suppressed:
            print(v.format() + f" — reason: {v.reason}")
        print(
            f"graftlint: {report.files} file(s), "
            f"{report.traced_functions} traced function(s), "
            f"{len(report.violations)} violation(s) "
            f"({len(fresh)} new vs baseline), "
            f"{len(report.suppressed)} suppressed"
        )
        if trace is not None:
            print(
                f"graftrace: {len(trace.thread_roots)} thread root(s), "
                f"{len(trace.shared_attrs)} shared attribute(s), "
                f"{trace.declared_attrs} guard declaration(s), "
                f"{len(trace.lock_edges)} lock-order edge(s), "
                f"{len(trace.lock_cycles)} cycle(s)"
            )
    return 1 if fresh else 0


def _trace_summary(report) -> dict:
    return {
        "thread_roots": report.thread_roots,
        "shared_attrs": report.shared_attrs,
        "declared_attrs": report.declared_attrs,
        "lock_nodes": report.lock_nodes,
        "lock_edges": [f"{a} -> {b}" for a, b in report.lock_edges],
        "lock_cycles": report.lock_cycles,
    }


def _trace_main(args) -> int:
    paths = args.paths or [_PACKAGE_DIR]
    root = os.path.dirname(_PACKAGE_DIR)
    report = trace_paths(paths, root=root)
    baseline = load_baseline(args.baseline)
    fresh = new_violations(report, baseline)
    if args.update_baseline:
        # Keep the lint pass's entries: this rewrite only owns the
        # concurrency rules' rows in the shared baseline file. Entries this
        # report RE-EMITS are dropped from the preserved set (a bare
        # graftrace-rule suppression is flagged by both grammars under the
        # same key — preserving AND re-adding would inflate its count).
        report_keys = {v.key for v in report.violations}
        preserve = {
            k: n
            for k, n in baseline.items()
            if k.rsplit("::", 1)[-1] not in R.CONCURRENCY_RULES
            and k not in report_keys
        }
        entries = save_baseline(report, args.baseline, preserve=preserve)
        print(f"baseline updated: {len(entries)} entrie(s) at {args.baseline}")
        return 0
    if args.json:
        doc = {
            "files": report.files,
            "rule_counts": report.counts(),
            "violations": [v.format() for v in report.violations],
            "new_violations": [v.format() for v in fresh],
            "suppressed": [v.format() for v in report.suppressed],
            "ok": not fresh,
        }
        doc.update(_trace_summary(report))
        print(json.dumps(doc))
    else:
        for v in report.violations:
            marker = "" if v.key in baseline else " [NEW]"
            print(v.format() + marker)
        for v in report.suppressed:
            print(v.format() + f" — reason: {v.reason}")
        roots = ", ".join(report.thread_roots) or "<none>"
        print(
            f"graftrace: {report.files} file(s); thread roots: {roots}; "
            f"{len(report.shared_attrs)} shared attribute(s), "
            f"{report.declared_attrs} guard declaration(s), "
            f"{len(report.lock_edges)} lock-order edge(s), "
            f"{len(report.lock_cycles)} cycle(s), "
            f"{len(report.violations)} violation(s) ({len(fresh)} new), "
            f"{len(report.suppressed)} suppressed"
        )
    return 1 if fresh else 0


def _proto_main(args) -> int:
    paths = args.paths or [_PACKAGE_DIR]
    root = os.path.dirname(_PACKAGE_DIR)
    report = proto_paths(paths, root=root)
    baseline = load_baseline(args.baseline)
    fresh = new_violations(report, baseline)
    if args.update_baseline:
        # This rewrite only owns the proto rules' rows in the shared file.
        report_keys = {v.key for v in report.violations}
        preserve = {
            k: n
            for k, n in baseline.items()
            if k.rsplit("::", 1)[-1] not in R.PROTO_RULES
            and k not in report_keys
        }
        entries = save_baseline(report, args.baseline, preserve=preserve)
        print(f"baseline updated: {len(entries)} entrie(s) at {args.baseline}")
        return 0
    if args.json:
        doc = {
            "files": report.files,
            "rule_counts": report.counts(),
            "violations": [v.format() for v in report.violations],
            "new_violations": [v.format() for v in fresh],
            "suppressed": [v.format() for v in report.suppressed],
            "lockstep_segments": report.lockstep_segments,
            "barrier_sequences": report.barrier_sequences,
            "persistence_points": report.persistence_points,
            "collective_functions": report.collective_functions,
            "ok": not fresh,
        }
        print(json.dumps(doc))
    else:
        for v in report.violations:
            marker = "" if v.key in baseline else " [NEW]"
            print(v.format() + marker)
        for v in report.suppressed:
            print(v.format() + f" — reason: {v.reason}")
        segs = ", ".join(sorted(report.lockstep_segments)) or "<none>"
        print(
            f"graftproto: {report.files} file(s); lockstep segments: {segs}; "
            f"{len(report.persistence_points)} persistence point(s), "
            f"{len(report.collective_functions)} collective function(s), "
            f"{len(report.violations)} violation(s) ({len(fresh)} new), "
            f"{len(report.suppressed)} suppressed"
        )
    return 1 if fresh else 0


def _modelcheck_main(args) -> int:
    verdict = model_check(
        seed=args.seed, smoke=args.smoke, scenarios=args.scenario or None
    )
    if args.json:
        print(json.dumps(verdict))
    else:
        for p in verdict["points"]:
            novel = " [novel]" if p in verdict.get("novel_points", ()) else ""
            print(f"modelcheck: point {p}{novel}")
        for f in verdict["failures"]:
            print(f"modelcheck: FAILED {f}")
        status = "OK" if verdict["ok"] else "FAILED"
        print(
            f"modelcheck: {status} — {verdict.get('num_points', 0)} "
            f"persistence point(s), {verdict.get('num_injections', 0)} "
            f"injection(s) over {len(verdict['scenarios'])} scenario(s), "
            f"schedule {str(verdict.get('schedule_sha256'))[:12]}"
        )
    return 0 if verdict["ok"] else 1


def _suppressions_main(args) -> int:
    from .graftlint import Linter, Report

    paths = args.paths or [_PACKAGE_DIR]
    root = os.path.dirname(_PACKAGE_DIR)
    linter = Linter(paths, root=root)
    linter.load(Report())
    rows = []
    for mod in linter.modules:
        for line, (rule, reason) in sorted(mod.suppressions.items()):
            rows.append(
                {
                    "file": mod.relpath,
                    "line": line,
                    "rule": rule,
                    "reason": reason or None,
                }
            )
    rows.sort(key=lambda r: (r["file"], r["line"]))
    reasonless = [r for r in rows if not r["reason"]]
    if args.json:
        print(
            json.dumps(
                {
                    "suppressions": rows,
                    "count": len(rows),
                    "reasonless": reasonless,
                    "ok": not reasonless,
                }
            )
        )
    else:
        for r in rows:
            why = r["reason"] or "<NO REASON — fix or remove>"
            print(f"{r['file']}:{r['line']}: {r['rule']} — {why}")
        print(
            f"suppressions: {len(rows)} total, {len(reasonless)} reason-less"
        )
    return 1 if reasonless else 0


def _check_config_main(args) -> int:
    ladder = None
    if args.bucket_ladder:
        ladder = []
        for part in filter(None, (p.strip() for p in args.bucket_ladder.split(","))):
            try:
                n, e = part.split("x")
                ladder.append((int(n), int(e)))
            except ValueError:
                # Malformed rung: hand the raw string to the checker, which
                # reports it as a one-line oob-bucket finding instead of a
                # parse traceback here.
                ladder.append(part)
    try:
        report = check_config(
            args.config, mode=args.mode, bucket_ladder=ladder, strict=False
        )
    except ConfigContractError as e:  # malformed beyond reporting
        print(f"check-config: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(report))
    else:
        for err in report["errors"]:
            print(f"check-config: [{err['code']}] {err['message']}")
        for s in report["skipped"]:
            print(f"check-config: skipped — {s}")
        status = "OK" if report["ok"] else "FAILED"
        extra = (
            f" (eval_shape {report['eval_shape_s']}s)"
            if report.get("eval_shape_s") is not None
            else ""
        )
        print(f"check-config: {status} [{report['mode']}]{extra}")
    return 0 if report["ok"] else 1


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.analysis",
        description="graftlint + static config contract checker",
    )
    sub = ap.add_subparsers(dest="cmd")
    lint = sub.add_parser(
        "lint", help="run graftlint + graftrace (the default command)"
    )
    lint.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    lint.add_argument("--json", action="store_true")
    lint.add_argument("--baseline", default=DEFAULT_BASELINE_PATH)
    lint.add_argument("--update-baseline", action="store_true")
    lint.add_argument(
        "--no-trace",
        action="store_true",
        help="lint only (skip the graftrace concurrency pass)",
    )
    tr = sub.add_parser(
        "trace", help="graftrace: thread topology + lock discipline"
    )
    tr.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    tr.add_argument("--json", action="store_true")
    tr.add_argument("--baseline", default=DEFAULT_BASELINE_PATH)
    tr.add_argument("--update-baseline", action="store_true")
    pr = sub.add_parser(
        "proto", help="graftproto: SPMD/barrier lockstep + incarnation contract"
    )
    pr.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    pr.add_argument("--json", action="store_true")
    pr.add_argument("--baseline", default=DEFAULT_BASELINE_PATH)
    pr.add_argument("--update-baseline", action="store_true")
    mc = sub.add_parser(
        "modelcheck", help="crash-consistency model checker (graftproto runtime)"
    )
    mc.add_argument("--seed", type=int, default=0)
    mc.add_argument(
        "--smoke",
        action="store_true",
        help="CI-bounded subset: elastic shrink + swap promote",
    )
    mc.add_argument(
        "--scenario",
        action="append",
        help="run only the named scenario(s) (repeatable)",
    )
    mc.add_argument("--json", action="store_true")
    sp = sub.add_parser(
        "suppressions", help="audit inline disables across all three grammars"
    )
    sp.add_argument("paths", nargs="*", help="files/dirs (default: the package)")
    sp.add_argument("--json", action="store_true")
    cc = sub.add_parser("check-config", help="static config contract check")
    cc.add_argument("config")
    cc.add_argument(
        "--mode",
        choices=("training", "prediction", "serving"),
        default="training",
    )
    cc.add_argument(
        "--bucket-ladder",
        default="",
        help='serving bucket shapes "NxE,NxE" to validate against the config',
    )
    cc.add_argument("--json", action="store_true")
    return ap


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # Default subcommand: bare invocation (or paths/flags only) means lint.
    known = (
        "lint",
        "trace",
        "proto",
        "modelcheck",
        "suppressions",
        "check-config",
        "-h",
        "--help",
    )
    if not argv or argv[0] not in known:
        argv = ["lint"] + argv
    args = build_parser().parse_args(argv)
    if args.cmd == "check-config":
        return _check_config_main(args)
    if args.cmd == "trace":
        return _trace_main(args)
    if args.cmd == "proto":
        return _proto_main(args)
    if args.cmd == "modelcheck":
        return _modelcheck_main(args)
    if args.cmd == "suppressions":
        return _suppressions_main(args)
    return _lint_main(args)


if __name__ == "__main__":
    sys.exit(main())
