"""graftlint — the framework-aware AST linter (rule catalogue: rules.py,
policy + examples: docs/STATIC_ANALYSIS.md).

How it decides what is "traced": each file is parsed once; function/lambda
definitions are indexed with qualnames; traced ROOTS are (a) functions
decorated with a jax transform (``@jax.jit``, ``@functools.partial(jax.jit,
...)``), (b) functions/lambdas passed as arguments to a transform call
(``jax.jit(f)``, ``lax.scan(body, ...)``, ``shard_map(_local, ...)``),
(c) nested definitions inside the framework's step-body factories
(rules.TRACED_FACTORIES — ``_step_body`` returns its closure, which static
analysis cannot see through), and (d) methods of flax ``nn.Module``
subclasses (they run under ``model.init``/``model.apply`` tracing). The
traced set is then propagated over the static call graph (name calls,
``self.`` method calls, and cross-module ``from ... import`` edges within the
linted file set) to a fixpoint; rules that only make sense in traced code run
on exactly that set.

The linter is intentionally conservative the other way for suppressions:
``# graftlint: disable=<rule>(<reason>)`` on the violation's line (or the
line above) suppresses it, and an empty reason is itself a violation —
an unexplained suppression is a prose invariant again, which is the failure
mode this module exists to end.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import rules as R

# One suppression grammar for all three passes: comments of the form
# ``graft{lint,race,proto}: disable=<rule>(<why>)`` are interchangeable (the
# rule id decides which pass it addresses; rules.RULES is the single
# catalogue).
_SUPPRESS_RE = re.compile(
    r"#\s*graft(?:lint|race|proto):\s*disable=([A-Za-z0-9_-]+)\s*(?:\(([^)]*)\))?"
)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclass
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str
    qualname: str
    suppressed: bool = False
    reason: Optional[str] = None

    @property
    def key(self) -> str:
        """Line-number-free identity for the committed baseline (stable
        across unrelated edits to the same file)."""
        return f"{self.path}::{self.qualname}::{self.rule}"

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule}: "
            f"{self.message} [{self.qualname}]{tag}"
        )


@dataclass
class Report:
    violations: List[Violation] = field(default_factory=list)
    suppressed: List[Violation] = field(default_factory=list)
    files: int = 0
    traced_functions: int = 0

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {rid: 0 for rid in R.RULES}
        for v in self.violations:
            out[v.rule] = out.get(v.rule, 0) + 1
        return out

    @property
    def ok(self) -> bool:
        return not self.violations


# --------------------------------------------------------------------- helpers
def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _own_walk(fn_node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    definitions (each nested def is its own FuncInfo)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, _FUNC_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _own_statements(body: Sequence[ast.stmt]) -> Iterable[ast.stmt]:
    """Statements of a function body in source order, recursing into control
    flow but not into nested function definitions."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, _FUNC_NODES) or isinstance(stmt, ast.ClassDef):
            continue
        for attr in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, attr, None)
            if inner:
                yield from _own_statements(inner)
        for handler in getattr(stmt, "handlers", ()) or ():
            yield from _own_statements(handler.body)


def _literal_int_positions(node: ast.AST) -> Tuple[int, ...]:
    """donate_argnums / static_argnums literal → positions tuple."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


@dataclass
class FuncInfo:
    module: "ModuleInfo"
    node: ast.AST  # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str
    name: str  # simple name ("<lambda>" for lambdas)
    parent: Optional["FuncInfo"]
    class_name: Optional[str]
    traced: bool = False
    calls: List[Tuple[str, ast.Call]] = field(default_factory=list)
    # graftrace: the set of thread roots this function may execute on
    # (populated by analysis/concurrency.py, unused by the lint pass).
    roots: Set[str] = field(default_factory=set)

    @property
    def line(self) -> int:
        return self.node.lineno


class ModuleInfo:
    """One parsed file: AST, import aliases, functions, suppressions."""

    def __init__(self, path: str, relpath: str, dotted: Optional[str]):
        self.path = path
        self.relpath = relpath
        self.dotted = dotted  # package-dotted module name, if inside a package
        with open(path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.tree = ast.parse(self.source, filename=path)
        self.aliases: Dict[str, str] = {}  # local name -> canonical dotted
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name -> (module, orig)
        self.functions: List[FuncInfo] = []
        self.func_by_node: Dict[ast.AST, FuncInfo] = {}
        self.toplevel: Dict[str, FuncInfo] = {}
        self.methods: Dict[Tuple[str, str], FuncInfo] = {}  # (class, meth)
        self.suppressions: Dict[int, Tuple[str, Optional[str]]] = {}
        self.module_classes: Set[str] = set()  # flax nn.Module subclasses
        self._collect_imports()
        self._collect_suppressions()
        self._collect_functions()

    # ------------------------------------------------------------ collection
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.aliases[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                        if alias.asname
                        else alias.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                src = self._resolve_from(node)
                for alias in node.names:
                    local = alias.asname or alias.name
                    if src is not None:
                        self.from_imports[local] = (src, alias.name)
                    # Names imported from libraries resolve dotted-wise too
                    # (``from jax import lax`` → lax.* = jax.lax.*).
                    base = src if src is not None else (node.module or "")
                    if base:
                        self.aliases[local] = f"{base}.{alias.name}"

    def _resolve_from(self, node: ast.ImportFrom) -> Optional[str]:
        """Absolute dotted module for a from-import (relative ones resolved
        against this module's package position)."""
        if node.level == 0:
            return node.module
        if self.dotted is None:
            return None
        parts = self.dotted.split(".")
        if len(parts) < node.level:
            return None
        base = parts[: len(parts) - node.level]
        if node.module:
            base = base + node.module.split(".")
        return ".".join(base)

    def _collect_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    reason = m.group(2)
                    reason = reason.strip() if reason else None
                    self.suppressions[tok.start[0]] = (m.group(1), reason)
        except tokenize.TokenError:
            pass

    def canonical(self, dotted: Optional[str]) -> Optional[str]:
        """Rewrite the first segment of a dotted name through the module's
        import aliases (``jnp.where`` → ``jax.numpy.where``)."""
        if not dotted:
            return dotted
        head, _, rest = dotted.partition(".")
        mapped = self.aliases.get(head, head)
        return f"{mapped}.{rest}" if rest else mapped

    def _collect_functions(self) -> None:
        mod = self

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self.stack: List[FuncInfo] = []
                self.class_stack: List[str] = []

            def _add(self, node: ast.AST, name: str) -> FuncInfo:
                parent = self.stack[-1] if self.stack else None
                cls = self.class_stack[-1] if self.class_stack else None
                prefix = (
                    parent.qualname + ".<locals>."
                    if parent
                    else (cls + "." if cls else "")
                )
                info = FuncInfo(
                    module=mod,
                    node=node,
                    qualname=prefix + name,
                    name=name,
                    parent=parent,
                    class_name=cls if not parent else None,
                )
                mod.functions.append(info)
                mod.func_by_node[node] = info
                if parent is None and cls is None:
                    mod.toplevel[name] = info
                if parent is None and cls is not None:
                    mod.methods[(cls, name)] = info
                return info

            def visit_ClassDef(self, node: ast.ClassDef) -> None:
                for base in node.bases:
                    d = mod.canonical(_dotted(base)) or ""
                    if d.split(".")[-1] == "Module":
                        mod.module_classes.add(node.name)
                self.class_stack.append(node.name)
                self.generic_visit(node)
                self.class_stack.pop()

            def _visit_fn(self, node: ast.AST, name: str) -> None:
                info = self._add(node, name)
                self.stack.append(info)
                self.generic_visit(node)
                self.stack.pop()
                # Record this function's outgoing calls (own nodes only).
                for sub in _own_walk(node):
                    if isinstance(sub, ast.Call):
                        d = _dotted(sub.func)
                        if d:
                            info.calls.append((d, sub))

            def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
                self._visit_fn(node, node.name)

            def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
                self._visit_fn(node, node.name)

            def visit_Lambda(self, node: ast.Lambda) -> None:
                self._visit_fn(node, "<lambda>")

        V().visit(self.tree)


# ---------------------------------------------------------------------- linter
class Linter:
    def __init__(self, paths: Sequence[str], root: Optional[str] = None):
        self.files = sorted(self._expand(paths))
        # Guard the derived root: commonpath raises on an empty list (typo'd
        # path → zero .py files) and on mixed absolute/relative paths.
        self.root = root or (
            os.path.commonpath(
                [os.path.dirname(os.path.abspath(f)) or "." for f in self.files]
            )
            if self.files
            else "."
        )
        self.modules: List[ModuleInfo] = []
        self.by_dotted: Dict[str, ModuleInfo] = {}

    @staticmethod
    def _expand(paths: Sequence[str]) -> List[str]:
        out: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = [
                        d for d in dirnames if d != "__pycache__"
                    ]
                    out.extend(
                        os.path.join(dirpath, f)
                        for f in filenames
                        if f.endswith(".py")
                    )
            elif p.endswith(".py"):
                out.append(p)
        return out

    def _dotted_name(self, path: str) -> Optional[str]:
        """hydragnn_tpu-rooted dotted module name, if the file is inside the
        package (used to resolve relative imports)."""
        norm = path.replace(os.sep, "/")
        marker = "hydragnn_tpu/"
        idx = norm.rfind(marker)
        if idx < 0:
            return None
        rel = norm[idx:].rsplit(".py", 1)[0]
        return rel.replace("/", ".").removesuffix(".__init__")

    # --------------------------------------------------------------- pipeline
    def load(self, report: Report) -> None:
        """Parse + index every file (shared with the graftrace pass, which
        subclasses this linter for the module/callgraph infrastructure)."""
        for path in self.files:
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                mod = ModuleInfo(path, rel, self._dotted_name(path))
            except SyntaxError as e:
                report.violations.append(
                    Violation(
                        rule="recompile-hazard",
                        path=rel,
                        line=e.lineno or 0,
                        col=0,
                        message=f"file does not parse: {e.msg}",
                        qualname="<module>",
                    )
                )
                continue
            self.modules.append(mod)
            if mod.dotted:
                self.by_dotted[mod.dotted] = mod
        report.files = len(self.modules)

    def run(self) -> Report:
        report = Report()
        self.load(report)

        self._mark_traced_roots()
        self._propagate_traced()
        report.traced_functions = sum(
            1 for m in self.modules for f in m.functions if f.traced
        )

        for mod in self.modules:
            self._lint_module(mod, report)
        report.violations.sort(key=lambda v: (v.path, v.line, v.col))
        report.suppressed.sort(key=lambda v: (v.path, v.line, v.col))
        return report

    # ------------------------------------------------------------ traced set
    def _is_transform(self, mod: ModuleInfo, dotted: Optional[str]) -> bool:
        if not dotted:
            return False
        canon = mod.canonical(dotted) or ""
        tail2 = ".".join(canon.split(".")[-2:])
        return (
            dotted in R.TRANSFORM_ENTRY_POINTS
            or canon in R.TRANSFORM_ENTRY_POINTS
            or tail2 in R.TRANSFORM_ENTRY_POINTS
        )

    def _mark_traced_roots(self) -> None:
        for mod in self.modules:
            for fn in mod.functions:
                node = fn.node
                # (a) transform decorators, incl. functools.partial(jax.jit,..)
                for dec in getattr(node, "decorator_list", ()):
                    d = _dotted(dec)
                    if self._is_transform(mod, d):
                        fn.traced = True
                    if isinstance(dec, ast.Call):
                        dd = mod.canonical(_dotted(dec.func)) or ""
                        if dd.split(".")[-1] == "partial" and dec.args:
                            if self._is_transform(mod, _dotted(dec.args[0])):
                                fn.traced = True
                        elif self._is_transform(mod, _dotted(dec.func)):
                            fn.traced = True
                # (c) nested defs inside the step-body factories
                p = fn.parent
                while p is not None:
                    if p.name in R.TRACED_FACTORIES:
                        fn.traced = True
                        break
                    p = p.parent
                # (d) flax Module methods
                if fn.class_name and fn.class_name in mod.module_classes:
                    fn.traced = True

            # (b) callables passed to transform calls
            for fn in mod.functions:
                for dotted, call in fn.calls:
                    if not self._is_transform(mod, dotted):
                        continue
                    cargs = list(call.args) + [
                        kw.value for kw in call.keywords
                    ]
                    for arg in cargs:
                        if isinstance(arg, ast.Lambda):
                            info = mod.func_by_node.get(arg)
                            if info:
                                info.traced = True
                        elif isinstance(arg, ast.Name):
                            target = self._resolve_local(
                                mod, fn, arg.id
                            )
                            if target:
                                target.traced = True
            # module-level transform calls (e.g. jax.jit(lambda ...) at
            # import): walk module body outside functions
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) and self._is_transform(
                    mod, _dotted(node.func)
                ):
                    for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        if isinstance(arg, ast.Lambda):
                            info = mod.func_by_node.get(arg)
                            if info:
                                info.traced = True

    def _resolve_local(
        self, mod: ModuleInfo, fn: Optional[FuncInfo], name: str
    ) -> Optional[FuncInfo]:
        """Resolve a simple callee name: nested defs of enclosing functions,
        then module-level functions, then cross-module from-imports."""
        scope = fn
        while scope is not None:
            for child in mod.functions:
                if child.parent is scope and child.name == name:
                    return child
            scope = scope.parent
        if name in mod.toplevel:
            return mod.toplevel[name]
        imp = mod.from_imports.get(name)
        if imp:
            src_mod = self.by_dotted.get(imp[0])
            if src_mod:
                return src_mod.toplevel.get(imp[1])
        return None

    def _resolve_call(
        self, mod: ModuleInfo, fn: FuncInfo, dotted: str
    ) -> Optional[FuncInfo]:
        parts = dotted.split(".")
        if len(parts) == 1:
            return self._resolve_local(mod, fn, parts[0])
        if parts[0] == "self" and len(parts) == 2 and fn.class_name:
            return mod.methods.get((fn.class_name, parts[1]))
        if parts[0] == "self" and len(parts) == 2 and fn.parent:
            # method of the class enclosing a nested function
            p = fn.parent
            while p is not None and p.class_name is None:
                p = p.parent
            if p is not None and p.class_name:
                return mod.methods.get((p.class_name, parts[1]))
        if len(parts) == 2:
            # module-alias call: alias.func where alias maps to a linted module
            canon = mod.canonical(parts[0])
            src_mod = self.by_dotted.get(canon or "")
            if src_mod:
                return src_mod.toplevel.get(parts[1])
        return None

    def _propagate_traced(self) -> None:
        changed = True
        while changed:
            changed = False
            for mod in self.modules:
                for fn in mod.functions:
                    if not fn.traced:
                        continue
                    for dotted, _ in fn.calls:
                        target = self._resolve_call(mod, fn, dotted)
                        if target is not None and not target.traced:
                            target.traced = True
                            changed = True

    # ------------------------------------------------------------------ rules
    def _emit(
        self,
        report: Report,
        mod: ModuleInfo,
        rule: str,
        node: ast.AST,
        message: str,
        qualname: str,
    ) -> None:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        v = Violation(
            rule=rule,
            path=mod.relpath,
            line=line,
            col=col,
            message=message,
            qualname=qualname,
        )
        for probe in (line, line - 1):
            sup = mod.suppressions.get(probe)
            if sup and sup[0] == rule and sup[1]:
                v.suppressed = True
                v.reason = sup[1]
                report.suppressed.append(v)
                return
        report.violations.append(v)

    def _lint_module(self, mod: ModuleInfo, report: Report) -> None:
        # Bare suppressions (missing or empty justification) + unknown rules.
        for line, (rule, reason) in sorted(mod.suppressions.items()):
            if rule not in R.RULES:
                report.violations.append(
                    Violation(
                        rule="suppression-without-reason",
                        path=mod.relpath,
                        line=line,
                        col=0,
                        message=f"suppression names unknown rule {rule!r}",
                        qualname="<module>",
                    )
                )
            elif not reason:
                report.violations.append(
                    Violation(
                        rule="suppression-without-reason",
                        path=mod.relpath,
                        line=line,
                        col=0,
                        message=(
                            f"disable={rule} needs a justification: "
                            f"# graftlint: disable={rule}(why this is safe)"
                        ),
                        qualname="<module>",
                    )
                )
        self._check_import_time(mod, report)
        for fn in mod.functions:
            guard_path = (
                fn.name in R.GUARD_PATH_FUNCTIONS
                or (
                    fn.traced
                    and any(
                        mod.relpath.endswith(g) for g in R.GUARD_PATH_MODULES
                    )
                )
            )
            collation = any(
                mod.relpath.endswith(c)
                for c in R.COLLATION_DETERMINISTIC_MODULES
            )
            if fn.traced:
                self._check_host_sync(mod, fn, report)
            if guard_path:
                self._check_cond_in_guard(mod, fn, report)
            self._check_nondeterminism(mod, fn, report, collation)
            self._check_donation(mod, fn, report)
            self._check_recompile_fn(mod, fn, report)
            self._check_pickle_load(mod, fn, report)

    # --- pickle-load-outside-compat
    def _check_pickle_load(
        self, mod: ModuleInfo, fn: FuncInfo, report: Report
    ) -> None:
        """The raw-pickle read path was deprecated in PR 16 (the GSHD convert
        CLI replaced it with digest-verified containers). EVERY surviving
        pickle.load/pickle.loads/torch.load site is a sanctioned v1-compat
        shim and carries a reasoned inline suppression; a new call site
        without one is a regression."""
        for node in _own_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.canonical(_dotted(node.func))
            if canon in R.PICKLE_LOAD_CALLS:
                self._emit(
                    report,
                    mod,
                    "pickle-load-outside-compat",
                    node,
                    f"{canon}() outside the sanctioned v1-compat shims — "
                    "the raw-pickle read path is deprecated (use the GSHD "
                    "convert CLI / digest-verified containers)",
                    fn.qualname,
                )

    # --- host-sync-in-step
    def _check_host_sync(
        self, mod: ModuleInfo, fn: FuncInfo, report: Report
    ) -> None:
        for node in _own_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in R.HOST_SYNC_METHODS
            ):
                self._emit(
                    report,
                    mod,
                    "host-sync-in-step",
                    node,
                    f".{node.func.attr}() forces a host sync inside a "
                    "step-reachable function",
                    fn.qualname,
                )
                continue
            canon = mod.canonical(_dotted(node.func))
            if canon in R.HOST_SYNC_DOTTED or (
                canon
                and canon.startswith("numpy.")
                and canon.split(".")[-1] in ("asarray", "array")
            ):
                self._emit(
                    report,
                    mod,
                    "host-sync-in-step",
                    node,
                    f"{canon} materializes a traced value on the host",
                    fn.qualname,
                )
                continue
            if (
                isinstance(node.func, ast.Name)
                and node.func.id in R.HOST_SYNC_BUILTINS
                and node.args
                and self._nonstatic_arg(node.args[0])
            ):
                self._emit(
                    report,
                    mod,
                    "host-sync-in-step",
                    node,
                    f"{node.func.id}() on a traced value is a host sync "
                    "(ConcretizationError under jit)",
                    fn.qualname,
                )

    @staticmethod
    def _nonstatic_arg(arg: ast.AST) -> bool:
        """True when the argument could be a traced value: not a literal and
        not shape/dtype metadata (static at trace time)."""
        if isinstance(arg, ast.Constant):
            return False
        for sub in ast.walk(arg):
            if isinstance(sub, ast.Attribute) and sub.attr in (
                "shape",
                "ndim",
                "dtype",
                "size",
            ):
                return False
        # len(x) of a traced array is static
        if (
            isinstance(arg, ast.Call)
            and isinstance(arg.func, ast.Name)
            and arg.func.id == "len"
        ):
            return False
        return True

    # --- cond-in-guard
    def _check_cond_in_guard(
        self, mod: ModuleInfo, fn: FuncInfo, report: Report
    ) -> None:
        flag_names: Set[str] = set()
        for node in _own_walk(fn.node):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                callee = _dotted(node.value.func) or ""
                canon = mod.canonical(callee) or ""
                if callee.split(".")[-1] == "_all_finite" or canon.endswith(
                    "numpy.isfinite"
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            flag_names.add(t.id)
        for node in _own_walk(fn.node):
            if isinstance(node, ast.Call):
                canon = mod.canonical(_dotted(node.func)) or ""
                tail2 = ".".join(canon.split(".")[-2:])
                if tail2 in ("lax.cond", "lax.switch"):
                    self._emit(
                        report,
                        mod,
                        "cond-in-guard",
                        node,
                        f"{tail2} in guard-path code breaks bit-inertness — "
                        "select with jnp.where instead",
                        fn.qualname,
                    )
            if isinstance(node, (ast.If, ast.IfExp)) and flag_names:
                for sub in ast.walk(node.test):
                    if (
                        isinstance(sub, ast.Name)
                        and isinstance(sub.ctx, ast.Load)
                        and sub.id in flag_names
                    ):
                        self._emit(
                            report,
                            mod,
                            "cond-in-guard",
                            node,
                            f"Python branch on all-finite flag {sub.id!r} — "
                            "the guard must select with jnp.where",
                            fn.qualname,
                        )
                        break

    # --- nondeterminism
    def _check_nondeterminism(
        self, mod: ModuleInfo, fn: FuncInfo, report: Report, collation: bool
    ) -> None:
        if not (fn.traced or collation):
            return
        for node in _own_walk(fn.node):
            if not isinstance(node, ast.Call):
                continue
            canon = mod.canonical(_dotted(node.func)) or ""
            msg = None
            if canon.startswith("numpy.random."):
                attr = canon.split(".")[-1]
                if attr == "default_rng" and not (node.args or node.keywords):
                    msg = "np.random.default_rng() without a seed"
                elif attr not in R.SEEDED_NP_RANDOM:
                    msg = f"unseeded global-RNG call {canon}"
            elif canon.split(".")[0] == "random" and "." in canon:
                msg = f"stdlib global-RNG call {canon}"
            elif fn.traced and canon in (
                "time.time",
                "time.perf_counter",
                "time.monotonic",
            ):
                msg = f"{canon}() wall-clock read"
            elif collation and canon == "time.time":
                msg = "time.time() entropy"
            elif canon.endswith("datetime.now") or canon.endswith(
                "datetime.utcnow"
            ):
                msg = f"{canon}() wall-clock entropy"
            if msg:
                where = "traced" if fn.traced else "collation-deterministic"
                self._emit(
                    report,
                    mod,
                    "nondeterminism",
                    node,
                    f"{msg} in {where} code",
                    fn.qualname,
                )

    # --- use-after-donate
    def _class_donating(
        self, mod: ModuleInfo, cls: str
    ) -> Dict[str, Tuple[int, ...]]:
        """Class-level donating bindings (``self.X = make_train_step(...)``),
        computed ONCE per class (they depend only on the class's methods,
        not on which method is being linted)."""
        cache = getattr(mod, "_class_donating_cache", None)
        if cache is None:
            cache = {}
            mod._class_donating_cache = cache  # type: ignore[attr-defined]
        if cls in cache:
            return cache[cls]
        donating: Dict[str, Tuple[int, ...]] = {}
        for other in mod.functions:
            if other.class_name != cls:
                continue
            for node in _own_walk(other.node):
                if not (
                    isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                ):
                    continue
                pos = self._donated_positions(mod, node.value)
                if not pos:
                    continue
                for t in node.targets:
                    d = _dotted(t)
                    if d:
                        donating[d] = pos
        cache[cls] = donating
        return donating

    def _check_donation(
        self, mod: ModuleInfo, fn: FuncInfo, report: Report
    ) -> None:
        # Class-level: self.X = make_train_step(...) binds a donating step
        # visible from every method of the class.
        cls = fn.class_name
        p = fn.parent
        while cls is None and p is not None:
            cls = p.class_name
            p = p.parent
        donating = dict(self._class_donating(mod, cls)) if cls else {}
        # Function-local bindings.
        for node in _own_walk(fn.node):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                pos = self._donated_positions(mod, node.value)
                if pos:
                    for t in node.targets:
                        d = _dotted(t)
                        if d:
                            donating[d] = pos
        if not donating:
            return

        if isinstance(fn.node, ast.Lambda):  # expression body: no statements
            return
        body = fn.node.body
        statements = list(_own_statements(body))
        # Loop bodies are walked twice so a donation in iteration k is seen by
        # iteration k+1's loads.
        loop_tails: List[ast.stmt] = []
        for stmt in statements:
            if isinstance(stmt, (ast.For, ast.While)):
                loop_tails.extend(_own_statements(stmt.body))
        dead: Dict[str, ast.Call] = {}
        for stmt in statements + loop_tails:
            self._donation_scan_stmt(
                mod, fn, stmt, donating, dead, report
            )

    def _donated_positions(
        self, mod: ModuleInfo, call: ast.Call
    ) -> Tuple[int, ...]:
        """Positions donated by the callable this call RETURNS (jax.jit with
        donate_argnums, or a known donating factory)."""
        canon = mod.canonical(_dotted(call.func)) or ""
        name = (_dotted(call.func) or "").split(".")[-1]
        if canon in ("jax.jit", "jit") or canon.endswith(".jit"):
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    return _literal_int_positions(kw.value) or ()
            return ()
        if name in R.DONATING_FACTORIES:
            return R.DONATING_FACTORIES[name]
        # functools.partial(jax.jit, donate_argnums=...) decorator-style
        if canon.split(".")[-1] == "partial" and call.args:
            inner = mod.canonical(_dotted(call.args[0])) or ""
            if inner.endswith("jit"):
                for kw in call.keywords:
                    if kw.arg == "donate_argnums":
                        return _literal_int_positions(kw.value)
        return ()

    def _donation_scan_stmt(
        self,
        mod: ModuleInfo,
        fn: FuncInfo,
        stmt: ast.stmt,
        donating: Dict[str, Tuple[int, ...]],
        dead: Dict[str, ast.Call],
        report: Report,
    ) -> None:
        calls_here: List[ast.Call] = []
        skip_nodes: Set[int] = set()
        for node in ast.walk(stmt):
            if isinstance(node, _FUNC_NODES):
                skip_nodes.update(id(s) for s in ast.walk(node))
        for node in ast.walk(stmt):
            if id(node) in skip_nodes or not isinstance(node, ast.Call):
                continue
            callee = _dotted(node.func)
            if callee in donating:
                calls_here.append(node)
        # 1) loads of already-dead names in this statement → violation
        # (a donating call's OWN args are included on purpose: f(s); f(s)
        # loads dead s at the second call and must be flagged)
        for node in ast.walk(stmt):
            if id(node) in skip_nodes:
                continue
            d = _dotted(node) if isinstance(node, (ast.Name, ast.Attribute)) else None
            if (
                d
                and d in dead
                and isinstance(getattr(node, "ctx", None), ast.Load)
            ):
                donation = dead[d]
                self._emit(
                    report,
                    mod,
                    "use-after-donate",
                    node,
                    f"{d!r} was donated at line {donation.lineno} "
                    f"({_dotted(donation.func)}(...)); its buffer is dead",
                    fn.qualname,
                )
                del dead[d]  # one report per donation
        # 2) donations made by this statement mark their args dead
        for c in calls_here:
            positions = donating[_dotted(c.func)]
            for pos in positions:
                if pos < len(c.args):
                    d = _dotted(c.args[pos])
                    if d:
                        dead[d] = c
        # 3) stores in this statement resurrect names (fresh binding)
        for node in ast.walk(stmt):
            if id(node) in skip_nodes:
                continue
            d = _dotted(node) if isinstance(node, (ast.Name, ast.Attribute)) else None
            if d and d in dead and isinstance(
                getattr(node, "ctx", None), ast.Store
            ):
                del dead[d]

    # --- recompile-hazard
    def _check_import_time(self, mod: ModuleInfo, report: Report) -> None:
        """jnp/jax.numpy device work executed at module import time — both
        module-level statements and class-body statements (a class body runs
        at import too; only function bodies are deferred)."""

        def scan(stmts: Sequence[ast.stmt], where: str) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.ClassDef):
                    scan(stmt.body, f"{where}{stmt.name}.")
                    continue
                for node in _own_walk(stmt):
                    if isinstance(node, ast.Call):
                        canon = mod.canonical(_dotted(node.func)) or ""
                        if canon.startswith("jax.numpy."):
                            self._emit(
                                report,
                                mod,
                                "recompile-hazard",
                                node,
                                f"{canon} at module import time compiles "
                                "and allocates before any entry point runs",
                                where + "<module>",
                            )

        scan(mod.tree.body, "")

    def _check_recompile_fn(
        self, mod: ModuleInfo, fn: FuncInfo, report: Report
    ) -> None:
        # jit-wrapper construction inside a loop: a fresh wrapper per
        # iteration re-traces and re-compiles every time. Nested function
        # bodies are excluded — a closure DEFINED in a loop defers its jit
        # construction to call time.
        for node in _own_walk(fn.node):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            deferred: Set[int] = set()
            for sub in ast.walk(node):
                if isinstance(sub, _FUNC_NODES):
                    deferred.update(id(s) for s in ast.walk(sub) if s is not sub)
            for sub in ast.walk(node):
                if id(sub) in deferred:
                    continue
                if isinstance(sub, ast.Call):
                    canon = mod.canonical(_dotted(sub.func)) or ""
                    if canon in ("jax.jit", "jit") or canon == "jax.pmap":
                        self._emit(
                            report,
                            mod,
                            "recompile-hazard",
                            sub,
                            f"{canon}(...) constructed inside a loop — each "
                            "iteration re-traces and re-compiles",
                            fn.qualname,
                        )
        # Unhashable literals at static positions of a locally-bound jit.
        static_pos: Dict[str, Tuple[int, ...]] = {}
        for node in _own_walk(fn.node):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                canon = mod.canonical(_dotted(node.value.func)) or ""
                if canon in ("jax.jit", "jit"):
                    for kw in node.value.keywords:
                        if kw.arg == "static_argnums":
                            pos = _literal_int_positions(kw.value)
                            if pos:
                                for t in node.targets:
                                    d = _dotted(t)
                                    if d:
                                        static_pos[d] = pos
        if static_pos:
            for node in _own_walk(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d not in static_pos:
                    continue
                for pos in static_pos[d]:
                    if pos < len(node.args) and isinstance(
                        node.args[pos],
                        (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp),
                    ):
                        self._emit(
                            report,
                            mod,
                            "recompile-hazard",
                            node.args[pos],
                            f"unhashable literal at static_argnums position "
                            f"{pos} of {d} — every call re-traces (or "
                            "raises); pass a tuple",
                            fn.qualname,
                        )


def lint_paths(paths: Sequence[str], root: Optional[str] = None) -> Report:
    """Lint files/directories; returns the Report (violations exclude
    properly-suppressed ones, which land in ``report.suppressed``)."""
    return Linter(paths, root=root).run()
