"""Static-analysis layer (docs/STATIC_ANALYSIS.md):

* ``graftlint`` — framework-aware AST linter guarding the invariants PR 1-3
  established in prose (host-sync-free step bodies, bit-inert guard,
  donation safety, recompile hygiene, collation determinism).
* ``check_config`` — static config/shape contract checker: ``jax.eval_shape``
  over model + loss + guarded step against the declared dataset descriptors
  and padded-arena buckets, before any device compile.
* ``no_recompile`` — process-wide recompile sentinel (the serve engine's
  executable-cache accounting, generalized).
* ``graftrace`` — static lock-discipline + thread-topology analyzer over the
  host concurrency layer (concurrency.py), with an opt-in runtime
  sanitizer half (tsan.py, ``HYDRAGNN_TSAN=1``).
* ``graftproto`` — static SPMD/barrier lockstep analyzer over the distributed
  control plane (proto.py: collective-lockstep, barrier-protocol,
  incarnation-contract), with a crash-consistency model checker as its
  runtime half (mck.py, ``modelcheck``).

CLI: ``python -m hydragnn_tpu.analysis`` lints the package;
``python -m hydragnn_tpu.analysis check-config <json>`` checks a config;
``proto`` / ``modelcheck`` / ``suppressions`` run the graftproto passes and
the suppression audit.

This package deliberately imports nothing heavy at module scope — the linter
half must stay usable (and fast) in contexts that never touch jax.
"""

from __future__ import annotations

from .baseline import (
    DEFAULT_BASELINE_PATH,
    load_baseline,
    new_violations,
    save_baseline,
)
from .concurrency import TraceReport, trace_paths
from .contracts import ConfigContractError, check_config, gate_config
from .graftlint import Report, Violation, lint_paths
from .mck import CrashInjected, model_check
from .proto import ProtoReport, proto_paths
from .sentinel import RecompileError, compile_count, no_recompile

__all__ = [
    "ConfigContractError",
    "CrashInjected",
    "DEFAULT_BASELINE_PATH",
    "ProtoReport",
    "RecompileError",
    "Report",
    "TraceReport",
    "Violation",
    "check_config",
    "compile_count",
    "gate_config",
    "lint_paths",
    "load_baseline",
    "model_check",
    "new_violations",
    "no_recompile",
    "proto_paths",
    "save_baseline",
    "trace_paths",
]
