"""Post-training weight quantization for the serve quantized arm
(docs/PRECISION.md "Serving arms").

``--precision int8`` is per-tensor symmetric weight quantization with bf16
activations: every weight matrix is snapped to a 255-level int8 grid
(``scale = max|w| / 127``, ``q = round(w / scale)``), the forward runs the
model's bf16 compute path over the DEQUANTIZED weights. This is the standard
"fake-quant" (simulated-quantization) serving arm: numerics are exactly those
of int8 weight storage — every served output is bit-identical to what a true
int8-weight executable would produce after its dequantize — while the
executable itself stays a plain XLA program the whole bucket-ladder /
graftcache machinery already handles. True int8 HBM residency is a hardware
follow-up (ROADMAP item 3); the TOLERANCE contract and cache-key separation
land here and carry over unchanged.

Policy: leaves with ``ndim >= 2`` (the matmul weights — where the bytes and
the MXU work are) quantize; biases, BatchNorm statistics, and other vectors/
scalars stay exact, matching standard post-training-quantization practice.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

INT8_LEVELS = 127  # symmetric: [-127, 127], -128 unused


def quantize_tensor_symmetric(w: np.ndarray) -> Tuple[np.ndarray, float]:
    """Per-tensor symmetric int8 quantization → (int8 values, f32 scale).
    An all-zero tensor quantizes to zeros with scale 0.0 (dequantizes
    exactly)."""
    w = np.asarray(w, np.float32)
    amax = float(np.max(np.abs(w))) if w.size else 0.0
    if amax == 0.0:
        return np.zeros(w.shape, np.int8), 0.0
    scale = amax / INT8_LEVELS
    q = np.clip(np.rint(w / scale), -INT8_LEVELS, INT8_LEVELS)
    return q.astype(np.int8), scale


def dequantize_tensor(q: np.ndarray, scale: float) -> np.ndarray:
    return (np.asarray(q, np.float32) * np.float32(scale)).astype(np.float32)


def fake_quantize_params(params: Any) -> Tuple[Any, Dict[str, Any]]:
    """Round-trip every weight matrix of a param pytree through the int8 grid
    (quantize → dequantize, values land exactly on representable points).
    Returns ``(quantized params, report)`` where the report carries tensor
    counts and the worst per-tensor quantization step (the grid resolution —
    an upper bound on any single weight's rounding error)."""
    import jax

    quantized = 0
    kept = 0
    max_step = 0.0

    def leaf(w):
        nonlocal quantized, kept, max_step
        arr = np.asarray(w)
        if arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating):
            q, scale = quantize_tensor_symmetric(arr)
            quantized += 1
            max_step = max(max_step, scale)
            return dequantize_tensor(q, scale)
        kept += 1
        return arr

    out = jax.tree_util.tree_map(leaf, params)
    report = {
        "scheme": "per-tensor symmetric int8 weights, bf16 activations",
        "tensors_quantized": quantized,
        "tensors_kept_exact": kept,
        "max_quant_step": max_step,
    }
    return out, report
