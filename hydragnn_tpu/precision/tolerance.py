"""Shared numerical-tolerance machinery (docs/PRECISION.md "Tolerance gate").

ONE tolerance implementation for every place the stack compares a reduced- or
alternate-precision computation against a reference:

* kernel certification (``ops/pallas_segment.certify_pallas``) — the fwd/grad
  gates that used to be module-local pins now live here as
  :data:`KERNEL_CERT_GATE`, so kernel certification and quantized serving can
  never drift apart on what "within tolerance" means;
* the serve engine's quantized arm (``serve/engine.py check_tolerance``) —
  the bit-exactness contract relaxes to :func:`tolerance_report` ONLY for
  ``--precision bf16|int8``;
* ``bench.py --precision`` — the step-matched convergence delta and the
  quantized-arm diff stats are computed through the same helpers.

Everything here is host-side numpy: no jax import, so the ops layer can
consume the gate constants without an import cycle.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class ToleranceGate:
    """A forward (and optionally gradient) max-abs-error bound.

    ``check`` returns a verdict dict rather than raising: every consumer
    (certify artifact, serve gate, bench section) embeds the verdict in its
    own report and decides locally whether a failure is fatal."""

    fwd: float
    grad: Optional[float] = None

    def check(
        self, fwd_err: float, grad_err: Optional[float] = None
    ) -> Dict[str, Any]:
        ok = float(fwd_err) < self.fwd
        verdict: Dict[str, Any] = {
            "ok": ok,
            "fwd_err": float(fwd_err),
            "tol": self.fwd,
        }
        if self.grad is not None and grad_err is not None:
            grad_ok = float(grad_err) < self.grad
            verdict.update(
                grad_err=float(grad_err), tol_grad=self.grad,
                ok=ok and grad_ok,
            )
        return verdict


# The kernel-certification pins, verbatim from certify_pallas (see the long
# rationale comment there: forward 5e-4 is kernel-grade strict; gradient 5e-3
# is the ANALYTIC worst case of an accurate-mean kernel at near-degenerate
# segments, not slack). certify_pallas consumes THESE constants.
KERNEL_CERT_GATE = ToleranceGate(fwd=5e-4, grad=5e-3)


def max_abs_diff(a: Any, b: Any) -> float:
    """Max absolute elementwise difference, computed in f64 (the certify
    convention — the comparison must not round in the dtype under test)."""
    a64 = np.asarray(a, np.float64)
    b64 = np.asarray(b, np.float64)
    if a64.shape != b64.shape:
        raise ValueError(
            f"shape mismatch in tolerance comparison: {a64.shape} vs {b64.shape}"
        )
    if a64.size == 0:
        return 0.0
    return float(np.max(np.abs(a64 - b64)))


def tolerance_report(
    outputs: Sequence[Any],
    reference: Sequence[Any],
    bound: float,
    names: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Per-head + overall max-abs-diff of ``outputs`` against ``reference``
    under one forward ``bound`` → the serve quantized-arm gate verdict.

    Also carries the reference dynamic range per head so a diff is readable
    as a relative error without re-running the reference."""
    if len(outputs) != len(reference):
        raise ValueError(
            f"{len(outputs)} outputs vs {len(reference)} reference heads"
        )
    heads: List[Dict[str, Any]] = []
    worst = 0.0
    for i, (out, ref) in enumerate(zip(outputs, reference)):
        diff = max_abs_diff(out, ref)
        ref64 = np.asarray(ref, np.float64)
        span = float(np.max(np.abs(ref64))) if ref64.size else 0.0
        heads.append(
            {
                "head": names[i] if names else f"head_{i}",
                "max_abs_diff": diff,
                "ref_max_abs": span,
                "rel_diff": diff / span if span > 0 else None,
            }
        )
        worst = max(worst, diff)
    gate = ToleranceGate(fwd=float(bound))
    verdict = gate.check(worst)
    verdict["per_head"] = heads
    return verdict
