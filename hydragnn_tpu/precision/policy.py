"""The precision policy layer (docs/PRECISION.md).

``Training.precision`` selects the TRAINING arithmetic end to end:

* ``"f32"`` (or absent) — the seed behavior. The compiled step is
  byte-identical to a build without this module loaded (locked by
  tests/test_precision.py): no loss-scale state, no extra casts, nothing.
* ``"bf16"`` — bf16 compute with f32 master weights plus DYNAMIC loss
  scaling. The model's existing ``compute_dtype`` mechanism does the casting
  (params + features cast INSIDE the differentiated function, so gradients
  accumulate against the f32 masters — trainer._apply_model); this module
  adds the loss-scale state machine that makes bf16's narrow exponent range
  survivable: the loss is multiplied by a running scale before
  ``value_and_grad``, gradients are unscaled before the optimizer, and an
  overflow (non-finite unscaled grads) SKIPS the update and backs the scale
  off — the in-jit half of the StepGuard non-finite policy
  (docs/FAULT_TOLERANCE.md), which the guard's host half then counts,
  flight-records, and (on a persistent streak) rolls back around.

The scale update lives INSIDE the compiled step (it must ride ``lax.scan``
epochs per-step, not per-chunk), as pure ``jnp.where`` selects — the same
no-``lax.cond`` rule the guard follows so fusion boundaries never move.

Serving arms (``--precision f32|bf16|int8``) are validated here; the int8
weight grid lives in :mod:`.quantize`, the relaxed gate in
:mod:`.tolerance`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional

from flax import struct

TRAIN_PRECISIONS = ("f32", "bf16")
SERVE_PRECISIONS = ("f32", "bf16", "int8")
QUANTIZED_SERVE_PRECISIONS = ("bf16", "int8")


@dataclass(frozen=True)
class LossScaleConfig:
    """Dynamic loss-scale knobs (the ``Training.loss_scale`` block).

    Defaults follow the standard dynamic-scaling recipe: start high, halve on
    every overflow, double after ``growth_interval`` consecutive clean steps,
    clamp to [min_scale, max_scale]."""

    init: float = 2.0**15
    backoff: float = 0.5
    growth: float = 2.0
    growth_interval: int = 200
    min_scale: float = 1.0
    max_scale: float = 2.0**24

    @classmethod
    def from_config(cls, cfg: Optional[Dict[str, Any]]) -> "LossScaleConfig":
        cfg = dict(cfg or {})
        known = {
            "init", "backoff", "growth", "growth_interval",
            "min_scale", "max_scale",
        }
        unknown = sorted(set(cfg) - known)
        if unknown:
            # A typo'd knob must never silently train with defaults — this
            # feeds the same bad-precision line the value checks do.
            raise ValueError(
                f"loss_scale has unknown key(s) {unknown}; valid knobs: "
                f"{sorted(known)}"
            )
        out = cls(
            init=float(cfg.get("init", cls.init)),
            backoff=float(cfg.get("backoff", cls.backoff)),
            growth=float(cfg.get("growth", cls.growth)),
            growth_interval=int(cfg.get("growth_interval", cls.growth_interval)),
            min_scale=float(cfg.get("min_scale", cls.min_scale)),
            max_scale=float(cfg.get("max_scale", cls.max_scale)),
        )
        out.validate()
        return out

    def validate(self) -> None:
        """The loss-scale sanity contract (mirrored by contracts.check_config
        as a static ``bad-precision`` finding)."""
        if self.init <= 0:
            raise ValueError(f"loss_scale.init {self.init} must be > 0")
        if not (0.0 < self.backoff < 1.0):
            raise ValueError(
                f"loss_scale.backoff {self.backoff} must be in (0, 1) — it "
                "SHRINKS the scale on overflow"
            )
        if self.growth <= 1.0:
            raise ValueError(
                f"loss_scale.growth {self.growth} must be > 1 — it GROWS the "
                "scale after clean steps"
            )
        if self.growth_interval < 1:
            raise ValueError(
                f"loss_scale.growth_interval {self.growth_interval} must be >= 1"
            )
        if not (0.0 < self.min_scale <= self.init <= self.max_scale):
            raise ValueError(
                "loss_scale bounds must satisfy 0 < min_scale <= init <= "
                f"max_scale (got min={self.min_scale} init={self.init} "
                f"max={self.max_scale})"
            )


@dataclass(frozen=True)
class PrecisionPolicy:
    """Resolved training precision: compute dtype + loss-scale config."""

    mode: str  # "bf16" (f32 resolves to None — no policy object at all)
    compute_dtype: str
    loss_scale: LossScaleConfig

    @staticmethod
    def resolve(
        precision: Optional[str], loss_scale_cfg: Optional[Dict[str, Any]] = None
    ) -> Optional["PrecisionPolicy"]:
        """``Training.precision`` + ``Training.loss_scale`` → policy, or None
        for the seed f32 path. Unknown strings and int8-for-training raise
        (the runtime mirror of the check_config gate)."""
        if precision in (None, "", "f32"):
            return None
        if precision == "int8":
            raise ValueError(
                "Training.precision='int8' is not a training mode — int8 is "
                "a quantized SERVING arm (--precision int8); train with "
                "'bf16' and quantize the checkpoint at serve time"
            )
        if precision != "bf16":
            raise ValueError(
                f"Training.precision {precision!r} is not one of "
                f"{TRAIN_PRECISIONS}"
            )
        return PrecisionPolicy(
            mode="bf16",
            compute_dtype="bfloat16",
            loss_scale=LossScaleConfig.from_config(loss_scale_cfg),
        )


# --------------------------------------------------------- in-jit scale state
@struct.dataclass
class LossScaleState:
    """Device-side dynamic-scale state. Rides in ``TrainState.loss_scale`` so
    it threads through scan carries, guard snapshots, and donation unchanged.
    Not persisted by checkpoints — a resumed run re-warms its scale, which
    dynamic scaling recovers in ~growth_interval steps."""

    scale: Any
    good_steps: Any


def make_loss_scale_state(cfg: LossScaleConfig) -> LossScaleState:
    import jax.numpy as jnp

    return LossScaleState(
        scale=jnp.asarray(cfg.init, jnp.float32),
        good_steps=jnp.zeros((), jnp.int32),
    )


def loss_scale_update(ls, ok, cfg: LossScaleConfig):
    """One step of the dynamic-scale state machine, inside the jit.

    ``ok`` is the step's all-finite flag over the UNSCALED loss/grads.
    Returns ``(new_state, grew)``: overflow → scale * backoff (floored),
    streak of ``growth_interval`` clean steps → scale * growth (capped);
    pure ``where`` selects, per the guard's fusion-boundary rule."""
    import jax.numpy as jnp

    good = jnp.where(ok, ls.good_steps + 1, 0)
    grew = jnp.logical_and(ok, good >= cfg.growth_interval)
    scale = jnp.where(
        ok,
        jnp.where(
            grew,
            jnp.minimum(ls.scale * cfg.growth, cfg.max_scale),
            ls.scale,
        ),
        jnp.maximum(ls.scale * cfg.backoff, cfg.min_scale),
    )
    new = ls.replace(
        scale=scale, good_steps=jnp.where(grew, 0, good).astype(jnp.int32)
    )
    return new, grew


# ------------------------------------------------------------- host half
class LossScaleMonitor:
    """Host-side observability of the in-jit scale machine — the precision
    analog of StepGuard's counting half, called next to it by the driver on
    every step/chunk update (docs/PRECISION.md "Telemetry").

    Emits: ``train/loss_scale`` gauge, ``prec/overflow`` / ``prec/backoff`` /
    ``prec/growth`` counters (plus FaultCounters ``loss_scale_backoff`` so
    the end-of-run fault report carries it), and a flight-recorder event per
    backoff batch — the ring then shows WHEN the scale moved next to the
    collate/h2d/device spans of the step that overflowed."""

    def __init__(self, verbosity: int = 0):
        self.verbosity = verbosity
        self.overflows = 0
        self.growths = 0

    def after_update(self, driver, metrics) -> None:
        from ..faults.counters import FaultCounters
        from ..telemetry import graftel as telemetry
        from ..utils.print_utils import print_distributed

        ls = getattr(driver.state, "loss_scale", None)
        if ls is None:
            return
        scale = float(ls.scale)
        telemetry.gauge("train/loss_scale", scale)
        overflows = int(round(float(metrics.get("overflow", 0.0))))
        growths = int(round(float(metrics.get("scale_growths", 0.0))))
        if overflows:
            self.overflows += overflows
            telemetry.counter("prec/overflow", overflows)
            # One backoff fires per overflowing step, so the counts alias —
            # kept as two names because dashboards read them as cause/effect.
            telemetry.counter("prec/backoff", overflows)
            FaultCounters.inc("loss_scale_backoff", overflows)
            telemetry.event(
                "prec/loss_scale_backoff",
                overflows=overflows,
                scale=scale,
            )
            print_distributed(
                self.verbosity,
                f"precision: {overflows} overflow step(s), "
                f"loss scale now {scale:g}",
            )
        if growths:
            self.growths += growths
            telemetry.counter("prec/growth", growths)
