"""graftprec — the end-to-end precision policy layer (docs/PRECISION.md).

One policy surface threaded through trainer, guard, serve, and cache:

* training: ``Training.precision = "f32" | "bf16"`` — bf16 compute with f32
  master weights plus dynamic loss scaling (:mod:`.policy`); ``"f32"``
  compiles the byte-identical seed step.
* serving: ``--precision f32 | bf16 | int8`` — a tolerance-gated quantized
  arm (:mod:`.quantize` for the int8 weight grid, :mod:`.tolerance` for the
  gate the bit-exactness contract relaxes to in quantized mode only).
* kernels: certification tolerances (:data:`KERNEL_CERT_GATE`) are the SAME
  gate implementation the quantized serve arm uses — one definition of
  "within tolerance" for the whole stack.
"""

from .policy import (
    QUANTIZED_SERVE_PRECISIONS,
    SERVE_PRECISIONS,
    TRAIN_PRECISIONS,
    LossScaleConfig,
    LossScaleMonitor,
    LossScaleState,
    PrecisionPolicy,
    loss_scale_update,
    make_loss_scale_state,
)
from .quantize import (
    dequantize_tensor,
    fake_quantize_params,
    quantize_tensor_symmetric,
)
from .tolerance import (
    KERNEL_CERT_GATE,
    ToleranceGate,
    max_abs_diff,
    tolerance_report,
)

__all__ = [
    "KERNEL_CERT_GATE",
    "LossScaleConfig",
    "LossScaleMonitor",
    "LossScaleState",
    "PrecisionPolicy",
    "QUANTIZED_SERVE_PRECISIONS",
    "SERVE_PRECISIONS",
    "TRAIN_PRECISIONS",
    "ToleranceGate",
    "dequantize_tensor",
    "fake_quantize_params",
    "loss_scale_update",
    "make_loss_scale_state",
    "max_abs_diff",
    "quantize_tensor_symmetric",
    "tolerance_report",
]
