"""graftelastic — elastic data-parallel training over the graftmesh harness
(docs/DISTRIBUTED.md "Elastic runbook").

PR 14 left ``Training.elastic`` as validated metadata: the supervisor
persisted the launch topology and nothing acted on membership. This module is
the acting half — a membership/heartbeat layer over the PR-14 rendezvous and
a world-transition protocol, built so tier-1 can actually run it (worker
threads over the loopback harness; the spawn path rides the same
``ProxyRendezvous`` mailbox):

* :class:`MembershipTracker` — heartbeat/membership state. Workers beat
  through the rendezvous one-way mailbox (``LoopbackRendezvous.post`` /
  ``ProxyRendezvous.post``); the coordinator drains the mailbox and declares
  a worker dead when its last beat ages past ``Training.elastic.heartbeat_s``
  (or immediately, on a rendezvous abort naming the corpse). Joins and clean
  leaves are posted the same way.
* :func:`shard_schedule` — the deterministic re-shard: one GLOBAL per-epoch
  batch plan (the unsharded loader's own shuffled plan) consumed
  window-by-window, ``world`` batches per lockstep step. Every batch is
  consumed exactly once per epoch NO MATTER how many transitions happen
  mid-epoch, per-rank views are disjoint by construction, and the tail
  window pads with empty (all-masked) batches instead of wrapping — the
  documented wrap-pad divergence from ``GraphDataLoader``'s round-robin
  dealing (an elastic epoch must conserve the sample multiset exactly; a
  wrap would double-count tail samples every transition). The same dealing
  contract holds for an out-of-core GSHD corpus: ``StreamingGraphLoader``
  (datasets/stream.py, docs/DATA_PLANE.md) exposes identical
  ``num_shards``/``shard_rank`` views and a live ``reshard()`` for world
  transitions — elastic training never requires the corpus in host RAM.
* :class:`ElasticTrainer` — the world-transition protocol. On a membership
  change within ``[min_workers, max_workers]``: quiesce at the next step
  boundary, checkpoint through the existing v2 layer (atomic, digest
  verified), rebuild the mesh + compiled step for the NEW world size,
  restore through the fallback chain (``checkpoint.io.load_verified_chain``
  + :func:`~hydragnn_tpu.checkpoint.io.verify_elastic_handoff`), and resume
  from the persisted cursor. A DIRTY death (rendezvous abort) degrades
  gracefully: shrink below the corpse and resume from the last periodic
  checkpoint instead of dying; a join grows back up to ``max_workers``,
  with graftcache hydrating previously-seen-topology executables (the
  ``mesh`` CacheKey component already distinguishes them). A kill DURING a
  transition is survivable by the incarnation contract: the handoff save is
  atomic, so the next incarnation restores either the pre- or post-handoff
  state — never a torn one.

Drills: ``benchmarks/elastic_drills.py`` (kill / join-under-load / churn /
kill-during-transition) -> ``bench.py --elastic`` -> ``ELASTIC_rNN.json``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..analysis import tsan
from ..telemetry import graftel as telemetry
from .loopback import (
    LoopbackError,
    LoopbackRendezvous,
    LoopbackWorker,
    run_workers,
)

HEARTBEAT_TAG = "heartbeat"


class ElasticError(RuntimeError):
    """An elastic world failed: below min_workers, torn handoff, or a
    transition that cannot complete."""


class WorkerKilled(ElasticError):
    """A drill-injected dirty worker death (the SIGKILL analog for the
    in-process harness)."""

    def __init__(self, worker_id: str):
        super().__init__(f"worker {worker_id} killed")
        self.worker_id = worker_id


class TransitionKilled(ElasticError):
    """A drill-injected death INSIDE a world transition — after the handoff
    checkpoint landed, before the new world resumed (the incarnation-contract
    drill)."""


# --------------------------------------------------------------------- config
@dataclass(frozen=True)
class ElasticConfig:
    """The ``Training.elastic`` knobs (validated by the bad-mesh contract,
    analysis/contracts.py)."""

    min_workers: int = 1
    max_workers: int = 8
    heartbeat_s: float = 5.0

    def __post_init__(self):
        if not (1 <= self.min_workers <= self.max_workers):
            raise ValueError(
                f"elastic range [{self.min_workers}, {self.max_workers}] is "
                "unsatisfiable — need 1 <= min_workers <= max_workers"
            )
        if self.heartbeat_s <= 0:
            raise ValueError(
                f"heartbeat_s must be positive, got {self.heartbeat_s}"
            )

    @classmethod
    def from_training(cls, training_cfg: Optional[dict]) -> Optional["ElasticConfig"]:
        """The config's ``Training.elastic`` block as an :class:`ElasticConfig`
        (None when elasticity is not configured). Malformed blocks raise an
        ACTIONABLE ValueError — direct supervisor-CLI launches reach this
        before any config gate runs, and a raw AttributeError on
        ``"elastic": "yes"`` would bury the bad-mesh diagnosis."""
        block = (training_cfg or {}).get("elastic")
        if not block:
            return None
        if not isinstance(block, dict):
            raise ValueError(
                "Training.elastic must be a dict of worker-range knobs "
                "(min_workers/max_workers/heartbeat_s), got "
                f"{type(block).__name__} — see the bad-mesh contract "
                "(docs/DISTRIBUTED.md)"
            )
        try:
            return cls(
                min_workers=int(block.get("min_workers", 1)),
                max_workers=int(block.get("max_workers", 8)),
                heartbeat_s=float(block.get("heartbeat_s", 5.0)),
            )
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"Training.elastic is malformed ({e}) — min_workers/"
                "max_workers must be ints >= 1 with min <= max, heartbeat_s "
                "a positive number (docs/DISTRIBUTED.md)"
            ) from e

    def admits(self, world: int) -> bool:
        return self.min_workers <= world <= self.max_workers


# ----------------------------------------------------------------- membership
@dataclass(frozen=True)
class MembershipChange:
    """One detected membership delta (the quiesce trigger)."""

    dead: Tuple[str, ...] = ()
    left: Tuple[str, ...] = ()
    joined: Tuple[str, ...] = ()

    def __bool__(self) -> bool:
        return bool(self.dead or self.left or self.joined)


class MembershipTracker:
    """Heartbeat/membership state shared between worker heartbeat pumps and
    the coordinator's poll loop.

    ``heartbeat``/``join``/``request_leave`` are called from worker (and
    pump) threads; ``poll``/``alive`` from the coordinator — every field is
    under one lock, registered with the tsan drill
    (benchmarks/tsan_drill.py ``_elastic_drill``)."""

    def __init__(
        self,
        heartbeat_s: float,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.heartbeat_s = float(heartbeat_s)
        self._clock = clock
        self._lock = tsan.instrument_lock(
            threading.Lock(), "MembershipTracker._lock"
        )
        self._beats: Dict[str, float] = {}  # guarded-by: self._lock
        self._dead: set = set()  # guarded-by: self._lock
        self._leaves: set = set()  # guarded-by: self._lock
        self._joins: List[str] = []  # guarded-by: self._lock
        self._log: List[dict] = []  # guarded-by: self._lock

    # ------------------------------------------------------------- worker side
    def join(self, worker_id: str) -> None:
        """Announce a (new or returning) worker; its first beat is implicit."""
        now = self._clock()
        with self._lock:
            fresh = worker_id not in self._beats
            self._beats[worker_id] = now
            self._dead.discard(worker_id)
            if fresh:
                self._joins.append(worker_id)
                self._log.append({"event": "join", "worker": worker_id, "t": now})

    def heartbeat(self, worker_id: str) -> None:
        tsan.yield_point("elastic.membership.heartbeat")
        with self._lock:
            self._beats[worker_id] = self._clock()

    def request_leave(self, worker_id: str) -> None:
        """A clean, announced leave — quiesce at the next step boundary
        instead of waiting for the heartbeat deadline."""
        with self._lock:
            self._leaves.add(worker_id)
            self._log.append(
                {"event": "leave_requested", "worker": worker_id, "t": self._clock()}
            )

    def forget(self, worker_id: str) -> None:
        """Remove every trace of a worker (refused join, permanent removal):
        it neither ages into a death nor resurfaces as an arrival."""
        with self._lock:
            self._beats.pop(worker_id, None)
            self._dead.discard(worker_id)
            self._leaves.discard(worker_id)
            self._joins = [w for w in self._joins if w != worker_id]

    def mark_dead(self, worker_id: str) -> None:
        """Out-of-band death report (a rendezvous abort names the corpse
        faster than the heartbeat deadline can)."""
        with self._lock:
            self._dead.add(worker_id)
            self._log.append(
                {"event": "marked_dead", "worker": worker_id, "t": self._clock()}
            )

    def drain(self, posts: Sequence[Tuple[int, Any]]) -> int:
        """Fold rendezvous-mailbox heartbeat posts (``(rank, payload)`` with
        ``payload["wid"]``) into the beat table; returns how many landed."""
        n = 0
        for _rank, payload in posts:
            wid = (payload or {}).get("wid") if isinstance(payload, dict) else None
            if wid:
                self.heartbeat(str(wid))
                n += 1
        return n

    # -------------------------------------------------------- coordinator side
    def alive(self, now: Optional[float] = None) -> set:
        """Workers whose last beat is within the heartbeat deadline and that
        were not explicitly marked dead."""
        now = self._clock() if now is None else now
        with self._lock:
            return {
                wid
                for wid, t in self._beats.items()
                if wid not in self._dead and now - t <= self.heartbeat_s
            }

    def last_beat(self, worker_id: str) -> Optional[float]:
        with self._lock:
            return self._beats.get(worker_id)

    def poll(self, expected: Sequence[str]) -> MembershipChange:
        """One coordinator poll: which of ``expected`` died (missed deadline
        or marked dead), which asked to leave, and which new workers joined.
        Consumed deltas are cleared — a change is reported exactly once."""
        now = self._clock()
        with self._lock:
            dead = tuple(
                wid
                for wid in expected
                if wid in self._dead
                or (
                    wid in self._beats
                    and now - self._beats[wid] > self.heartbeat_s
                )
            )
            left = tuple(w for w in self._leaves if w in expected and w not in dead)
            joined = tuple(w for w in self._joins if w not in expected)
            self._leaves -= set(left)
            # Every announcement is consumed by the poll that saw it: a
            # member's own (stale) join must not resurface as an arrival
            # after it later leaves the roster.
            self._joins = []
            for wid in dead:
                self._dead.add(wid)
                self._beats.pop(wid, None)
            for wid in left:
                self._beats.pop(wid, None)
                self._log.append({"event": "left", "worker": wid, "t": now})
            if dead:
                self._log.append(
                    {"event": "declared_dead", "workers": list(dead), "t": now}
                )
        return MembershipChange(dead=dead, left=left, joined=joined)

    def log(self) -> List[dict]:
        with self._lock:
            return list(self._log)


class HeartbeatPump:
    """One worker's heartbeat thread: posts ``{"wid": ...}`` into the
    rendezvous mailbox (the coordinator drains it into the tracker) every
    ``interval_s`` until stopped. The pump dying WITH its worker is the
    point — a dirty death stops the beats and the deadline fires."""

    def __init__(
        self,
        rdv: LoopbackRendezvous,
        rank: int,
        worker_id: str,
        interval_s: float,
    ):
        self._rdv = rdv
        self._rank = rank
        self.worker_id = worker_id
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run,
            name=f"elastic-heartbeat-{worker_id}",
            daemon=True,
        )

    def _run(self) -> None:
        while not self._stop.is_set():
            self._rdv.post(
                self._rank, {"wid": self.worker_id}, tag=HEARTBEAT_TAG
            )
            self._stop.wait(self.interval_s)

    def start(self) -> "HeartbeatPump":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(5.0)


# ----------------------------------------------------------- deterministic re-shard
def shard_window(
    num_batches: int, cursor: int, world: int
) -> List[Optional[int]]:
    """ONE lockstep step's per-rank window: rank ``r`` takes global batch
    ``cursor + r`` (``None`` = an empty padding batch past the tail). THE
    dealing rule — the segment loop (`ElasticTrainer._run_segment`) and the
    whole-epoch :func:`shard_schedule` both consume it, so the tested
    exactly-once/disjoint properties and the production dealing can never
    diverge."""
    if world < 1:
        raise ValueError(f"world must be >= 1, got {world}")
    return [
        cursor + r if cursor + r < num_batches else None for r in range(world)
    ]


def shard_schedule(
    num_batches: int, cursor: int, world: int
) -> List[List[Optional[int]]]:
    """The deterministic elastic re-shard over one epoch's GLOBAL batch plan:
    :func:`shard_window` repeated from ``cursor`` to the tail. Pure function
    of ``(num_batches, cursor, world)`` — a world transition at any cursor
    resumes with the remaining window untouched, so per-epoch batch
    consumption is exactly once regardless of transitions and per-rank views
    are disjoint by construction (tests/test_elastic.py pins both)."""
    steps: List[List[Optional[int]]] = []
    c = max(0, int(cursor))
    while c < num_batches:
        steps.append(shard_window(num_batches, c, world))
        c += world
    return steps


# ---------------------------------------------------------------- drill schedule
@dataclass
class ElasticEvent:
    """One scheduled drill event, keyed on the global step counter:

    * ``kill``  — worker ``worker`` dies DIRTY at this step (no quiesce);
    * ``leave`` — worker ``worker`` announces a clean leave;
    * ``join``  — a new worker named ``worker`` asks to join;
    * ``kill_transition`` — the NEXT transition at/after this step dies
      after its handoff checkpoint (the incarnation-contract drill).
    """

    step: int
    kind: str
    worker: Optional[str] = None


class ElasticSchedule:
    """Thread-safe drill schedule: workers consult ``kill_due`` per step,
    the leader consults ``control_events`` / ``transition_kill_due`` —
    each event fires exactly once."""

    KINDS = ("kill", "leave", "join", "kill_transition")

    def __init__(self, events: Optional[Sequence[ElasticEvent]] = None):
        for e in events or ():
            if e.kind not in self.KINDS:
                raise ValueError(f"unknown elastic event kind {e.kind!r}")
        self._lock = tsan.instrument_lock(
            threading.Lock(), "ElasticSchedule._lock"
        )
        self._pending: List[ElasticEvent] = sorted(
            events or (), key=lambda e: e.step
        )  # guarded-by: self._lock

    def kill_due(self, worker_id: str, step: int) -> bool:
        with self._lock:
            for e in self._pending:
                if e.kind == "kill" and e.worker == worker_id and step >= e.step:
                    self._pending.remove(e)
                    return True
        return False

    def control_events(self, step: int) -> List[ElasticEvent]:
        """Leader-side: due leave/join events (consumed)."""
        with self._lock:
            due = [
                e
                for e in self._pending
                if e.kind in ("leave", "join") and step >= e.step
            ]
            for e in due:
                self._pending.remove(e)
        return due

    def transition_kill_due(self, step: int) -> bool:
        with self._lock:
            for e in self._pending:
                if e.kind == "kill_transition" and step >= e.step:
                    self._pending.remove(e)
                    return True
        return False


# --------------------------------------------------------------- the trainer
class ElasticTrainer:
    """Supervisor-driven elastic DP training over the loopback harness.

    One instance owns the model/optimizer/loader and drives segments: a
    segment is a lockstep run at a fixed world size; between segments the
    world transitions (quiesce → v2 handoff checkpoint → rebuild mesh +
    re-shard → verified restore → resume). The loader must be UNSHARDED
    (``num_shards=1``) and single-bucket — the global plan IS the shard
    authority; :func:`shard_schedule` deals it.
    """

    def __init__(
        self,
        model,
        optimizer,
        loader,
        elastic: ElasticConfig,
        run_path: str,
        name: str = "elastic",
        compile_cache: Optional[str] = None,
        checkpoint_every_steps: int = 4,
        keep_last_k: int = 3,
        grad_sync: str = "single",
        seed: int = 0,
    ):
        import jax

        from ..models.create import init_model_variables
        from ..train.trainer import create_train_state

        if getattr(loader, "num_shards", 1) != 1:
            raise ElasticError(
                "ElasticTrainer needs the UNSHARDED loader (num_shards=1): "
                "the global batch plan is the shard authority and "
                "shard_schedule deals it per world size"
            )
        if getattr(loader, "num_buckets", 1) != 1:
            raise ElasticError(
                "ElasticTrainer requires a single-bucket loader (one static "
                "pad shape) — multi-bucket elastic stacking is future work"
            )
        self.model = model
        self.optimizer = optimizer
        self.loader = loader
        self.elastic = elastic
        self.run_path = run_path
        self.name = name
        self.compile_cache = compile_cache
        self.checkpoint_every_steps = int(checkpoint_every_steps)
        self.keep_last_k = int(keep_last_k)
        self.grad_sync = grad_sync
        self.rng = jax.random.PRNGKey(seed)
        if len(jax.devices()) < elastic.max_workers:
            raise ElasticError(
                f"elastic max_workers={elastic.max_workers} needs that many "
                f"devices; {len(jax.devices())} visible — pin XLA_FLAGS="
                "--xla_force_host_platform_device_count"
            )
        variables = init_model_variables(model, next(iter(loader)))
        self.state = create_train_state(model, variables, optimizer)
        self._steps: Dict[int, Any] = {}  # world -> compiled DP step
        self._epoch_cache: Dict[int, list] = {}  # epoch -> collated batches
        self.tracker = MembershipTracker(elastic.heartbeat_s)
        # Leader-only writes ordered by the rendezvous lockstep contract;
        # the coordinator reads them strictly after run_workers' join.
        self.global_step = 0  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract; coordinator reads after join)
        self.incarnation = 0
        self.transitions: List[dict] = []
        self.loss_trace: List[dict] = []  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract; coordinator reads after join)
        self.checkpoints_written = 0  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract; coordinator reads after join)
        # Drill observability: every checkpointed (epoch, cursor) position —
        # "zero lost progress beyond the last checkpoint" asserts the resumed
        # position is a member — and the per-epoch batch-consumption ledger
        # backing the exactly-once conservation gate (reset to the restored
        # cursor on rollback, so the ledger tracks the SURVIVING trajectory).
        self.save_log: List[dict] = []  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract; coordinator reads after join)
        self.consumed: Dict[int, set] = {}  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract; coordinator reads after join)
        self.epoch_sizes: Dict[int, int] = {}
        self.segment_log: List[dict] = []
        self._joined_serial = 0
        self._exec_registry = None
        self._cache_fingerprint = ""
        if compile_cache:
            import hashlib

            from ..cache import ExecutableRegistry, ExecutableStore
            from ..checkpoint.format import param_fingerprint

            self._exec_registry = ExecutableRegistry(
                ExecutableStore(compile_cache), name="elastic"
            )
            # Program identity follows the TrainingDriver convention: the
            # param/opt tree fingerprints + module repr — NEVER the run name,
            # so a restarted incarnation (or a second trainer over the same
            # store) hydrates the same entries.
            self._cache_fingerprint = hashlib.sha256(
                (
                    param_fingerprint(self.state.params)
                    + param_fingerprint(
                        {"opt": self.state.opt_state, "bstats": self.state.batch_stats}
                    )
                    + repr(model)
                ).encode()
            ).hexdigest()

    # ------------------------------------------------------------- checkpoints
    @property
    def run_dir(self) -> str:
        import os

        return os.path.join(self.run_path, self.name)

    def _save(
        self, state, epoch: int, cursor: int, world: int, num_batches: int
    ) -> None:
        """The handoff/periodic checkpoint: the existing v2 save path plus
        the elastic meta block :func:`verify_elastic_handoff` consumes.
        ``state`` is passed explicitly — mid-segment saves run on the leader
        worker thread against the segment's live state cell."""
        from ..checkpoint.io import elastic_handoff_meta, save_model

        meta = {
            "epoch": epoch,
            "elastic": elastic_handoff_meta(
                world_size=world,
                epoch=epoch,
                cursor=cursor,
                incarnation=self.incarnation,
                global_step=self.global_step,
                num_batches=num_batches,
            ),
        }
        save_model(
            {"params": state.params, "batch_stats": state.batch_stats},
            state.opt_state,
            self.name,
            path=self.run_path,
            meta=meta,
            keep_last_k=self.keep_last_k,
        )
        self.checkpoints_written += 1
        self.save_log.append(
            {"epoch": int(epoch), "cursor": int(cursor), "world": int(world)}
        )

    def _restore(self, new_world: int) -> Tuple[int, int]:
        """Verified restore through the fallback chain; returns the resume
        ``(epoch, cursor)`` after the world-size-independent handoff
        assertions (checkpoint/io.py)."""
        import jax
        import numpy as np

        from ..checkpoint.io import load_verified_chain, verify_elastic_handoff

        template = {
            "params": self.state.params,
            "batch_stats": self.state.batch_stats,
        }
        new_vars, opt_state, meta, _report = load_verified_chain(
            template, self.run_dir, self.name, self.state.opt_state
        )
        handoff = verify_elastic_handoff(
            meta,
            new_world,
            min_workers=self.elastic.min_workers,
            max_workers=self.elastic.max_workers,
        )
        state = self.state.replace(
            params=new_vars["params"],
            batch_stats=new_vars["batch_stats"],
            opt_state=opt_state,
        )
        # Normalize EVERY leaf to host memory: arrays still committed to the
        # OLD world's mesh devices (state.step survives the replace above)
        # would poison the NEW world's dispatch — the world-size-independent
        # handoff means the new mesh re-places everything itself.
        self.state = jax.tree_util.tree_map(
            lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, state
        )
        if handoff.get("global_step") is not None:  # 0 is a real position
            self.global_step = int(handoff["global_step"])
        epoch, cursor = int(handoff["epoch"]), int(handoff["cursor"])
        # Rewind the consumption ledger to the restored trajectory: batches
        # past the checkpointed cursor (and any later epoch) replay.
        self.consumed[epoch] = set(range(cursor))
        for later in [e for e in self.consumed if e > epoch]:
            del self.consumed[later]
        return epoch, cursor

    # ------------------------------------------------------------ compiled step
    def _step_for(self, world: int):
        """The compiled shard_map DP step for a ``world``-device data mesh,
        dispatched through the shared graftcache registry when configured —
        the ``mesh`` CacheKey component keeps each topology's executable
        distinct, so returning to a previously-seen world size hydrates
        instead of recompiling (the join-under-load drill's
        ``warmup_xla_compiles=0`` gate)."""
        import jax

        from ..train.trainer import make_train_step_dp
        from .distributed import make_mesh, mesh_descriptor

        cached = self._steps.get(world)
        if cached is not None:
            return cached
        mesh = make_mesh(data_axis=world, devices=jax.devices()[:world])
        step = make_train_step_dp(
            self.model,
            self.optimizer,
            mesh,
            donate=False,
            grad_sync=self.grad_sync,
        )
        reg = self._exec_registry
        if reg is None:
            dispatch = step
        else:
            from ..cache import CacheKey, tree_signature

            descriptor = mesh_descriptor(mesh)

            def dispatch(state, batch, rng, _step=step, _md=descriptor):
                exe, _outcome, _s = reg.lookup_or_compile(
                    ("elastic_step", world),
                    lambda: CacheKey.for_environment(
                        program="elastic_step",
                        config_fingerprint=self._cache_fingerprint,
                        flags=(f"grad_sync={self.grad_sync}",),
                        args_digest=tree_signature((state, batch, rng)),
                        mesh=_md,
                    ),
                    lambda: _step.lower(state, batch, rng),
                )
                return exe(state, batch, rng)

        self._steps[world] = dispatch
        return dispatch

    def _epoch_batches(self, epoch: int) -> list:
        """The epoch's GLOBAL batch plan, collated once (the unsharded
        loader's own per-epoch shuffle is the plan authority)."""
        cached = self._epoch_cache.get(epoch)
        if cached is None:
            self.loader.set_epoch(epoch)
            cached = list(self.loader)
            self._epoch_cache = {epoch: cached}  # one epoch resident at a time
            self.epoch_sizes[epoch] = len(cached)
        return cached

    # ---------------------------------------------------------------- segments
    def _run_segment(
        self,
        epoch: int,
        cursor: int,
        roster: List[str],
        schedule: ElasticSchedule,
    ) -> dict:
        """One lockstep segment at the fixed world ``len(roster)``: workers
        exchange their global batch indices per step, the leader dispatches
        the stacked shard_map step and broadcasts metrics + the control
        decision (continue / quiesce / epoch_done). Returns the leader's
        outcome dict. A dirty worker death aborts the rendezvous and raises
        ``LoopbackError`` (handled by :meth:`run`)."""
        import jax

        from ..train.trainer import stack_batches

        world = len(roster)
        batches = self._epoch_batches(epoch)
        dispatch = self._step_for(world)
        rdv = LoopbackRendezvous(world)
        tracker = self.tracker
        # Leader-owned mutable cells; ordered by the rendezvous lockstep
        # contract exactly as in loopback_train.
        cell = {"state": self.state, "outcome": None}  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract)
        since_ckpt = {"steps": 0}  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract)

        def leader_decision(worker_cursor: int) -> dict:
            """Post-step control: drain heartbeats, apply due drill events,
            poll membership, checkpoint on cadence. Leader-only."""
            tracker.drain(rdv.posts(HEARTBEAT_TAG))
            for ev in schedule.control_events(self.global_step):
                if ev.kind == "leave" and ev.worker in roster:
                    tracker.request_leave(ev.worker)
                elif ev.kind == "join":
                    # Admission happens in run() against the POST-leave
                    # roster (a leave + a join in the same quiesce is a
                    # net-zero resize, not a refusal); over-capacity joins
                    # are refused there, with telemetry.
                    tracker.join(ev.worker or self._next_worker_id())
            change = tracker.poll(roster)
            done = worker_cursor >= len(batches)
            if change:
                return {
                    "decision": "quiesce",
                    "cursor": worker_cursor,
                    "change": {
                        "dead": list(change.dead),
                        "left": list(change.left),
                        "joined": list(change.joined),
                    },
                }
            if done:
                return {"decision": "epoch_done", "cursor": worker_cursor}
            if (
                self.checkpoint_every_steps > 0
                and since_ckpt["steps"] >= self.checkpoint_every_steps
            ):
                self._save(
                    cell["state"], epoch, worker_cursor, world, len(batches)
                )
                since_ckpt["steps"] = 0
            return {"decision": "continue", "cursor": worker_cursor}

        def worker_fn(worker: LoopbackWorker) -> dict:
            wid = roster[worker.rank]
            tracker.join(wid)
            pump = HeartbeatPump(
                rdv, worker.rank, wid,
                interval_s=self.elastic.heartbeat_s / 4.0,
            ).start()
            local_cursor = cursor
            try:
                while True:
                    if schedule.kill_due(wid, self.global_step):
                        raise WorkerKilled(wid)
                    mine = shard_window(len(batches), local_cursor, world)[
                        worker.rank
                    ]
                    group = worker.exchange(mine, tag="elastic_step")
                    live_idx = [i for i in group if i is not None]
                    m = None
                    if worker.is_leader and live_idx:
                        stacked = stack_batches(
                            [batches[i] for i in live_idx], world
                        )
                        cell["state"], m = dispatch(
                            cell["state"], stacked, self.rng
                        )
                        self.global_step += 1
                        since_ckpt["steps"] += 1
                        self.consumed.setdefault(epoch, set()).update(live_idx)
                        self.loss_trace.append(
                            {
                                "step": self.global_step,
                                "epoch": epoch,
                                "world": world,
                                "loss": float(m["loss"])
                                / max(float(m["count"]), 1.0),
                            }
                        )
                    local_cursor += len(live_idx)
                    control = worker.broadcast(
                        leader_decision(local_cursor)
                        if worker.is_leader
                        else None,
                        src=0,
                        tag="elastic_control",
                    )
                    local_cursor = control["cursor"]
                    if control["decision"] != "continue":
                        worker.barrier("elastic_quiesce")
                        if worker.is_leader:
                            cell["outcome"] = control
                        return control
            finally:
                pump.stop()

        try:
            run_workers(world, worker_fn, rdv=rdv)
        finally:
            self.state = cell["state"]
        outcome = cell["outcome"]
        if outcome is None:  # pragma: no cover - run_workers raised first
            raise ElasticError("segment ended without a leader outcome")
        return outcome

    def _next_worker_id(self) -> str:
        self._joined_serial += 1
        return f"j{self._joined_serial}"

    # -------------------------------------------------------------- transitions
    def _transition(
        self,
        kind: str,
        reason: str,
        epoch: int,
        cursor: int,
        old_roster: List[str],
        new_roster: List[str],
        schedule: ElasticSchedule,
        save_first: bool,
    ) -> Tuple[int, int]:
        """The world-transition protocol: (handoff save when the old state is
        clean) → rebuild for the new world → verified restore → resume. The
        drill's ``kill_transition`` fires between the save and the restore —
        the atomic v2 install guarantees the next incarnation sees either the
        pre- or post-handoff checkpoint, never a torn one. Returns the
        resumed ``(epoch, cursor)``."""
        old_world, new_world = len(old_roster), len(new_roster)
        if new_world < self.elastic.min_workers:
            raise ElasticError(
                f"world shrank to {new_world} < min_workers="
                f"{self.elastic.min_workers} ({reason}) — an elastic run "
                "cannot degrade below its configured floor"
            )
        t0 = time.perf_counter()
        with telemetry.span(
            "elastic_transition", kind=kind, reason=reason,
            from_world=old_world, to_world=new_world,
        ):
            if save_first:
                # Collate only on the save path: a dirty-death transition
                # must not re-materialize a possibly-evicted epoch just to
                # measure a length it never uses.
                batches = self._epoch_batches(epoch)
                self._save(self.state, epoch, cursor, old_world, len(batches))
            if schedule.transition_kill_due(self.global_step):
                # The incarnation-contract drill: die AFTER the handoff
                # landed, BEFORE the new world resumed.
                raise TransitionKilled(
                    f"transition {old_world}->{new_world} killed post-handoff "
                    f"(incarnation {self.incarnation})"
                )
            resume_epoch, resume_cursor = self._restore(new_world)
            self._step_for(new_world)  # rebuild (or rehydrate) the mesh step
        wall = time.perf_counter() - t0
        entry = {
            "kind": kind,
            "reason": reason,
            "from_world": old_world,
            "to_world": new_world,
            "epoch": resume_epoch,
            "cursor": resume_cursor,
            "global_step": self.global_step,
            "incarnation": self.incarnation,
            "wall_s": round(wall, 4),
        }
        self.transitions.append(entry)
        telemetry.counter("elastic/transitions")
        # Counter family matches the entry's kind exactly (a net-zero-size
        # replacement — one leave + one join in the same quiesce — is a
        # "resize", never misfiled as a grow or shrink).
        telemetry.counter(f"elastic/{kind}s")
        telemetry.event("elastic/transition", **entry)
        if kind == "shrink" and reason == "worker_death":
            # Flight-dump trigger (docs/OBSERVABILITY.md): the timeline that
            # led into a dirty shrink, next to the checkpoint it resumed from.
            telemetry.flight_dump(
                "elastic_transition", run_dir=self.run_dir, extra=entry
            )
        return resume_epoch, resume_cursor

    # --------------------------------------------------------------------- run
    def run(
        self,
        num_epochs: int,
        start_world: int,
        schedule: Optional[ElasticSchedule] = None,
    ) -> dict:
        """Train ``num_epochs`` epochs starting at ``start_world`` workers,
        transitioning on every membership change the schedule (or a real
        tracker feed) produces. Returns the run report consumed by the drill
        matrix."""
        if not self.elastic.admits(start_world):
            raise ElasticError(
                f"start_world={start_world} outside the elastic range "
                f"[{self.elastic.min_workers}, {self.elastic.max_workers}]"
            )
        schedule = schedule or ElasticSchedule()
        roster = [f"w{i}" for i in range(start_world)]
        for wid in roster:
            self.tracker.join(wid)
        self.tracker.poll(roster)  # consume the initial joins
        epoch, cursor = 0, 0
        self._save(
            self.state, epoch, cursor, len(roster),
            len(self._epoch_batches(0)),
        )
        from ..analysis.sentinel import compile_count

        while epoch < num_epochs:
            c0 = compile_count()
            try:
                outcome = self._run_segment(epoch, cursor, roster, schedule)
            except LoopbackError as e:
                self._log_segment(epoch, len(roster), compile_count() - c0)
                # Only MEMBERSHIP failures degrade: an injected/real worker
                # death (WorkerKilled) or a rendezvous-level abort/broken
                # barrier (bare LoopbackError). A programming error in the
                # step (TypeError from dispatch, a shape bug) must surface —
                # shrinking and retrying the same broken step would bury the
                # root cause under bogus worker_death telemetry until the
                # min_workers floor kills the run anyway.
                cause = e.__cause__
                if cause is not None and not isinstance(
                    cause, (WorkerKilled, LoopbackError)
                ):
                    raise
                # Dirty death: graceful degradation — name the corpse, mark
                # it dead, shrink below it, resume from the last checkpoint.
                corpse = self._corpse_of(e, roster)
                self.tracker.mark_dead(corpse)
                self.tracker.poll(roster)
                telemetry.counter("elastic/worker_deaths")
                new_roster = [w for w in roster if w != corpse]
                epoch, cursor = self._retryable_transition(
                    "shrink", "worker_death", epoch, cursor,
                    roster, new_roster, schedule, save_first=False,
                )
                roster = new_roster
                continue
            self._log_segment(epoch, len(roster), compile_count() - c0)
            if outcome["decision"] == "epoch_done":
                epoch += 1
                cursor = 0
                if epoch < num_epochs:
                    self._epoch_batches(epoch)
                continue
            # Clean quiesce: apply the membership change, then transition.
            change = outcome["change"]
            cursor = outcome["cursor"]
            new_roster = [
                w
                for w in roster
                if w not in change["dead"] and w not in change["left"]
            ]
            room = self.elastic.max_workers - len(new_roster)
            admitted = list(change["joined"])[: max(0, room)]
            for refused in list(change["joined"])[max(0, room):]:
                # Over-capacity arrival: refuse LOUDLY and forget its beats —
                # a refused joiner must neither linger in the tracker nor
                # resurface as a ghost arrival later.
                telemetry.event(
                    "elastic/join_refused",
                    worker=refused,
                    world=len(new_roster),
                    max_workers=self.elastic.max_workers,
                )
                self.tracker.forget(refused)
            new_roster.extend(admitted)
            if new_roster == roster:
                # The quiesce's only content was refused arrivals: nothing
                # changed — resume the same world, no phantom transition.
                continue
            if len(new_roster) > len(roster):
                kind = "grow"
            elif len(new_roster) < len(roster):
                kind = "shrink"
            else:
                kind = "resize"  # same-size replacement (leave + join)
            if change["dead"]:
                reason = "worker_death"
            elif admitted and change["left"]:
                reason = "worker_replacement"
            elif admitted:
                reason = "worker_join"
            else:
                reason = "worker_leave"
            epoch, cursor = self._retryable_transition(
                kind, reason, epoch, cursor, roster, new_roster, schedule,
                save_first=True,
            )
            roster = new_roster
        final_loss = self._final_eval_loss()
        conservation = {
            e: self.consumed.get(e, set()) == set(range(size))
            for e, size in self.epoch_sizes.items()
        }
        return {
            "completed": True,
            "epochs": int(num_epochs),
            "final_world": len(roster),
            "roster": list(roster),
            "global_steps": self.global_step,
            "incarnations": self.incarnation,
            "checkpoints_written": self.checkpoints_written,
            "transitions": list(self.transitions),
            "loss_trace": list(self.loss_trace),
            "final_eval_loss": final_loss,
            "membership_log": self.tracker.log(),
            "save_log": list(self.save_log),
            "segment_log": list(self.segment_log),
            "epoch_conservation": conservation,
            "epoch_conservation_ok": all(conservation.values()),
        }

    def _log_segment(self, epoch: int, world: int, compiles: int) -> None:
        self.segment_log.append(
            {"epoch": int(epoch), "world": int(world), "compiles": int(compiles)}
        )

    def _retryable_transition(self, *args, **kwargs) -> Tuple[int, int]:
        """A transition killed mid-flight (the drill) is retried by the next
        incarnation: the handoff save already landed atomically, so the
        retry restores the exact saved state — the 'state never torn'
        contract the kill-during-transition drill asserts."""
        try:
            return self._transition(*args, **kwargs)
        except TransitionKilled as e:
            self.incarnation += 1
            telemetry.event(
                "elastic/transition_killed",
                incarnation=self.incarnation,
                error=str(e),
            )
            # The retry must not re-save: the interrupted incarnation's
            # handoff is the authoritative state.
            kwargs["save_first"] = False
            return self._transition(*args, **kwargs)

    @staticmethod
    def _corpse_of(err: LoopbackError, roster: List[str]) -> str:
        cause = err.__cause__
        if isinstance(cause, WorkerKilled):
            return cause.worker_id
        # An unattributed abort: blame the highest rank (deterministic) —
        # real deployments resolve this via the heartbeat deadline instead.
        return roster[-1]

    def _final_eval_loss(self) -> float:
        """World-independent convergence probe: the single-device eval step
        over epoch 0's fixed plan — comparable across elastic and
        fixed-world runs of the same seed (the parity gate's measurement)."""
        from ..train.trainer import make_eval_step

        eval_step = make_eval_step(self.model)
        total, count = 0.0, 0.0
        for batch in self._epoch_batches(0):
            m, _outputs = eval_step(self.state, batch)
            total += float(m["loss"])
            count += float(m["count"])
        return total / max(count, 1.0)


# ------------------------------------------------------- restart topology check
def check_restart_topology(
    mesh_meta: dict,
    world_size: int,
    graph_axis: int,
    elastic: Optional[ElasticConfig],
) -> Optional[dict]:
    """Consume the supervisor.json ``mesh`` block on restart: an incarnation
    resuming under a topology that CONTRADICTS the persisted world/axis
    metadata must fail loudly with both topologies named — unless
    ``Training.elastic`` admits the new world size, in which case the
    transition descriptor is returned for the caller to log (None = same
    topology). ``graph_axis`` changes are never elastic: the edge-sharding
    layout bakes into every compiled step and checkpointed batch-stat
    reduction."""
    if not mesh_meta:
        return None
    saved_world = mesh_meta.get("world_size")
    saved_axis = int(mesh_meta.get("graph_axis") or 1)
    if saved_axis != int(graph_axis or 1):
        raise RuntimeError(
            "restart topology contradiction: supervisor.json persisted "
            f"graph_axis={saved_axis} but this incarnation is launching with "
            f"graph_axis={graph_axis} — edge sharding is not elastic; "
            "restore the original axis or start a fresh run"
        )
    if saved_world is None or int(saved_world) == int(world_size):
        return None
    if elastic is None or not elastic.admits(int(world_size)):
        bounds = (
            f"[{elastic.min_workers}, {elastic.max_workers}]"
            if elastic is not None
            else "not configured"
        )
        raise RuntimeError(
            "restart topology contradiction: supervisor.json persisted "
            f"world_size={saved_world} but this incarnation sees "
            f"world_size={world_size}, and Training.elastic admits "
            f"{bounds} — a non-elastic run must restart at its launch "
            "topology (or configure Training.elastic to permit the change)"
        )
    return {
        "kind": "grow" if int(world_size) > int(saved_world) else "shrink",
        "from_world": int(saved_world),
        "to_world": int(world_size),
    }
