"""graftmesh loopback harness — the backend-portable distributed layer
tier-1 can actually run (docs/DISTRIBUTED.md).

The genuinely-multiprocess path (``jax.distributed`` rendezvous, one process
per host) is environmentally dead on the CPU backend: XLA:CPU raises
"Multiprocess computations aren't implemented" at the first cross-process
psum, so since PR 10 the 2-process suite was a precise skip and every
distributed claim rested on single-caller virtual-mesh unit tests. This
module restores REAL multi-worker coverage without cross-process XLA
collectives:

* ``LoopbackRendezvous`` — an in-process rendezvous for N logical workers
  (threads): named barriers with lockstep-divergence detection, allgather/
  exchange, broadcast. The host-coordination analog of
  ``jax.distributed``'s barrier/bootstrap, over ``threading`` primitives.
* ``run_workers`` — spawn N worker threads over one rendezvous; a worker
  death aborts the barriers so the rest fail loudly instead of hanging.
* ``loopback_train`` — the 2-process DP e2e, in process: each worker owns a
  rank-sharded loader view (the same ``num_shards``/``shard_rank`` dealing a
  real multi-process launch uses) and collates its OWN batches on its OWN
  thread; per step the workers exchange host batches through the rendezvous,
  the leader stacks ``[D, ...]`` and dispatches the shard_map DP step over a
  REAL >1-size device mesh (pinned fake topology —
  ``XLA_FLAGS=--xla_force_host_platform_device_count``), and every worker
  independently accumulates the psum-reduced metrics. Gradient all-reduce is
  the step's own psum over 'data' — actual XLA collectives over the virtual
  mesh, not a host emulation.
* ``ProxyRendezvous`` — the spawn-path twin: the same barrier/allgather
  protocol over a localhost TCP socket, for workers that really are separate
  OS processes (elastic supervisor coordination, spawn-mode drills). It
  coordinates HOSTS only; cross-process device collectives still need a
  backend with multiprocess support, which is why the spawned
  ``jax.distributed`` arm keeps its precise skip on CPU.

CLI (used by tests/run_suite_2proc.py as the loopback fallback)::

    python -m hydragnn_tpu.parallel.loopback <config.json> \
        [--workers 2] [--epochs N] [--thresholds "rmse mae maxae"]
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence

from ..analysis import tsan

_BARRIER_TIMEOUT_S = 300.0
# One-way mailbox post (heartbeats, membership announcements) read/write
# deadline. Named so the static config gate (contracts.bad-elastic-timing)
# can check Training.elastic.heartbeat_s against the SAME number the wire
# path actually uses.
_POST_TIMEOUT_S = 10.0


class LoopbackError(RuntimeError):
    """A loopback world failed: worker exception, lockstep divergence, or a
    broken/abandoned barrier."""


class LoopbackRendezvous:
    """In-process rendezvous for ``world_size`` worker threads.

    Collective calls must be made by ALL workers in the same order (the
    lockstep contract every distributed rendezvous imposes); named barriers
    verify the order and fail loudly on divergence instead of deadlocking."""

    def __init__(self, world_size: int, timeout_s: float = _BARRIER_TIMEOUT_S):
        if world_size < 1:
            raise ValueError(f"world_size must be >= 1, got {world_size}")
        self.world_size = int(world_size)
        self._lock = tsan.instrument_lock(
            threading.Lock(), "LoopbackRendezvous._lock"
        )
        # Exchange slots + per-round tag, written by every worker thread.
        self._slots: List[Any] = [None] * world_size  # guarded-by: self._lock
        self._tags: List[Any] = [None] * world_size  # guarded-by: self._lock
        self._aborted = False  # guarded-by: self._lock, dirty-reads(monotonic bool; a stale False only delays the LoopbackError by one barrier)
        # One-way mailbox (graftelastic, docs/DISTRIBUTED.md "Elastic
        # runbook"): non-collective posts — heartbeats, join/leave
        # announcements — that must NOT block on a barrier (a dead worker
        # would wedge them forever). tag -> [(rank, payload), ...].
        self._mailbox: dict = {}  # guarded-by: self._lock
        # Barrier is self-synchronizing; two phases per collective (publish /
        # consume) so a fast worker cannot overwrite a slot before every
        # peer has read the previous round.
        self._publish = threading.Barrier(world_size, timeout=timeout_s)
        self._consume = threading.Barrier(world_size, timeout=timeout_s)

    # ------------------------------------------------------------- lifecycle
    def abort(self) -> None:
        """Break every waiting/future barrier — called when a worker dies so
        the surviving workers raise instead of hanging to the timeout."""
        with self._lock:
            self._aborted = True
        self._publish.abort()
        self._consume.abort()

    def _wait(self, barrier: threading.Barrier, what: str) -> None:
        if self._aborted:
            raise LoopbackError(f"loopback world aborted before {what}")
        try:
            barrier.wait()
        except threading.BrokenBarrierError:
            raise LoopbackError(
                f"loopback barrier broken at {what} — a peer worker died or "
                "timed out (see the first worker error)"
            ) from None

    # ------------------------------------------------------------ collectives
    def exchange(self, rank: int, obj: Any, tag: str = "exchange") -> List[Any]:
        """Allgather: every worker contributes ``obj``; all receive the
        rank-ordered list. ``tag`` is the lockstep check — divergent call
        sites across workers are an immediate LoopbackError."""
        with self._lock:
            self._slots[rank] = obj
            self._tags[rank] = tag
        self._wait(self._publish, f"exchange({tag}) publish")
        with self._lock:
            out = list(self._slots)
            tags = list(self._tags)
        if any(t != tag for t in tags):
            self.abort()
            raise LoopbackError(
                f"lockstep divergence: worker {rank} at {tag!r}, peers at "
                f"{sorted(set(map(repr, tags)))}"
            )
        self._wait(self._consume, f"exchange({tag}) consume")
        return out

    def barrier(self, rank: int, name: str = "barrier") -> None:
        self.exchange(rank, None, tag=f"barrier:{name}")

    def broadcast(self, rank: int, obj: Any, src: int = 0, tag: str = "bcast") -> Any:
        return self.exchange(rank, obj if rank == src else None, tag=tag)[src]

    # --------------------------------------------------------------- mailbox
    def post(self, rank: int, payload: Any, tag: str = "post") -> None:
        """Non-collective one-way message (heartbeats, membership
        announcements): never blocks on a barrier, so a dying peer cannot
        wedge the sender."""
        with self._lock:
            self._mailbox.setdefault(tag, []).append((rank, payload))

    def posts(self, tag: str = "post") -> List[tuple]:
        """Drain (and clear) the mailbox for ``tag`` — the coordinator-side
        read feeding :class:`~hydragnn_tpu.parallel.elastic.MembershipTracker`."""
        with self._lock:
            return self._mailbox.pop(tag, [])


@dataclass
class LoopbackWorker:
    """One logical worker's handle: rank + world + the shared rendezvous."""

    rank: int
    world_size: int
    rdv: LoopbackRendezvous

    def exchange(self, obj: Any, tag: str = "exchange") -> List[Any]:
        return self.rdv.exchange(self.rank, obj, tag=tag)

    def barrier(self, name: str = "barrier") -> None:
        self.rdv.barrier(self.rank, name)

    def broadcast(self, obj: Any = None, src: int = 0, tag: str = "bcast") -> Any:
        return self.rdv.broadcast(self.rank, obj, src=src, tag=tag)

    @property
    def is_leader(self) -> bool:
        return self.rank == 0


def run_workers(
    world_size: int,
    fn: Callable[[LoopbackWorker], Any],
    rdv: Optional[LoopbackRendezvous] = None,
) -> List[Any]:
    """Run ``fn(worker)`` on ``world_size`` threads over one rendezvous.
    Returns rank-ordered results; the FIRST worker exception re-raises (the
    rendezvous is aborted first so no peer hangs)."""
    rdv = rdv if rdv is not None else LoopbackRendezvous(world_size)
    results: List[Any] = [None] * world_size
    # Append-only error log; list.append is GIL-atomic and each worker
    # appends at most once, so the join below observes a complete log.
    errors: List[tuple] = []  # guarded-by: none(append-only under the GIL; read only after join)

    def runner(rank: int) -> None:
        try:
            results[rank] = fn(LoopbackWorker(rank, world_size, rdv))
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            errors.append((rank, e))
            rdv.abort()

    threads = [
        threading.Thread(
            target=runner, args=(r,), name=f"mesh-worker-{r}", daemon=True
        )
        for r in range(world_size)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        errors.sort(key=lambda it: it[0])
        rank, err = errors[0]
        if isinstance(err, LoopbackError) and len(errors) > 1:
            # Barrier-broken errors are the SYMPTOM; surface a root cause.
            for r, e in errors:
                if not isinstance(e, LoopbackError):
                    rank, err = r, e
                    break
        raise LoopbackError(f"loopback worker {rank} failed: {err}") from err
    return results


# --------------------------------------------------------------- loopback e2e
def _shard_loader_view(loader, world_size: int, rank: int):
    """Rank ``rank``'s view of a loader: same dataset/head-spec/seed, dealt
    ``num_shards=world_size`` — the identical wrap-pad round-robin a real
    multi-process launch gets from create_dataloaders, so every worker yields
    the same number of identically-shaped batches per epoch."""
    from ..preprocess.dataloader import GraphDataLoader

    shard_batch = max(1, -(-loader.batch_size // world_size))
    return GraphDataLoader(
        loader.dataset,
        batch_size=shard_batch,
        shuffle=loader.shuffle,
        seed=loader.seed,
        num_shards=world_size,
        shard_rank=rank,
        head_types=loader.head_types,
        head_dims=loader.head_dims,
        edge_dim=loader.edge_dim,
        num_buckets=getattr(loader, "_num_buckets_requested", 1),
        reshuffle=loader.reshuffle,
        packing=loader.packing,
        ladder_step=loader.ladder_step,
    )


def loopback_train(
    config: dict,
    world_size: int = 2,
    num_epochs: Optional[int] = None,
    grad_sync: Optional[str] = None,
) -> List[dict]:
    """The 2-process DP e2e on the loopback harness: ``world_size`` worker
    threads, each with its own rank-sharded loader, lockstep-stepping ONE
    shard_map DP train step over a ``world_size``-device mesh; eval reduced
    the same way. Returns the rank-ordered per-worker result dicts — every
    worker's metrics are the globally psum-reduced values, so the workers
    must agree exactly (the property the old 2-process test asserted).

    The leader thread owns the TrainState and the compiled step; batches are
    exchanged host-side (numpy pytrees), the gradient all-reduce is the
    step's own psum over the 'data' mesh axis. Dispatch stays on the leader
    because a JAX runtime is process-global — exactly why the loopback world
    is threads, not processes, on backends without multiprocess collectives."""
    import jax
    import numpy as np

    from ..analysis.contracts import gate_config
    from ..models.create import create_model_config, init_model_variables
    from ..preprocess.load_data import dataset_loading_and_splitting
    from ..train.train_validate_test import EpochMetrics
    from ..train.trainer import (
        create_train_state,
        make_eval_step_dp,
        make_train_step_dp,
        stack_batches,
    )
    from ..utils.config_utils import update_config
    from ..utils.optimizer import select_optimizer
    from .distributed import make_mesh, mesh_descriptor

    if len(jax.devices()) < world_size:
        raise LoopbackError(
            f"loopback world of {world_size} needs {world_size} devices; "
            f"{len(jax.devices())} visible — pin XLA_FLAGS="
            "--xla_force_host_platform_device_count"
        )
    # Same env default as run_training: the raw→serialized dataset convert
    # lands next to the caller unless pointed elsewhere.
    import os

    os.environ.setdefault("SERIALIZED_DATA_PATH", os.getcwd())
    gate_config(config, mode="training")
    train_loader, val_loader, test_loader, _ = dataset_loading_and_splitting(
        config=config
    )
    config = update_config(config, train_loader, val_loader, test_loader)
    training_cfg = config["NeuralNetwork"]["Training"]
    epochs = int(num_epochs or training_cfg["num_epoch"])
    model = create_model_config(
        config=config["NeuralNetwork"]["Architecture"], verbosity=0
    )
    example = next(iter(train_loader))
    variables = init_model_variables(model, example)
    optimizer = select_optimizer(
        training_cfg["optimizer"], training_cfg["learning_rate"]
    )
    mesh = make_mesh(
        data_axis=world_size, devices=jax.devices()[:world_size]
    )
    step = make_train_step_dp(
        model, optimizer, mesh,
        grad_sync=grad_sync or training_cfg.get("grad_sync") or "single",
        grad_bucket_mb=float(training_cfg.get("grad_bucket_mb") or 4.0),
    )
    eval_step = make_eval_step_dp(model, mesh)
    # Leader-owned mutable cell: ONLY the rank-0 thread reads/writes it, and
    # every access is ordered by the exchange barriers around the step.
    cell = {"state": create_train_state(model, variables, optimizer)}  # guarded-by: external(leader-thread-only by the rendezvous lockstep contract)
    rng = jax.random.PRNGKey(0)

    def _reduce_epoch(worker, loader_view, dispatch):
        """One lockstep pass over a rank-sharded loader: exchange host
        batches, leader dispatches, every worker accumulates the reduced
        metrics independently."""
        metrics = EpochMetrics()
        it = iter(loader_view)
        while True:
            batch = next(it, None)
            group = worker.exchange(batch, tag="step_batches")
            if all(b is None for b in group):
                break
            live = [b for b in group if b is not None]
            m = None
            if worker.is_leader:
                stacked = stack_batches(live, world_size)
                m = dispatch(stacked)
            m = worker.broadcast(m, src=0, tag="step_metrics")
            metrics.update(m)
        return metrics.averages()

    def worker_fn(worker: LoopbackWorker) -> dict:
        train_view = _shard_loader_view(train_loader, world_size, worker.rank)
        val_view = _shard_loader_view(val_loader, world_size, worker.rank)
        history: dict = {"total_loss_train": [], "total_loss_val": []}

        def train_dispatch(stacked):
            cell["state"], m = step(cell["state"], stacked, rng)
            return m

        def eval_dispatch(stacked):
            m, _outputs = eval_step(cell["state"], stacked)
            return m

        for epoch in range(epochs):
            train_view.set_epoch(epoch)
            loss, _ = _reduce_epoch(worker, train_view, train_dispatch)
            vloss, _ = _reduce_epoch(worker, val_view, eval_dispatch)
            history["total_loss_train"].append(float(loss))
            history["total_loss_val"].append(float(vloss))
        worker.barrier("epochs_done")
        return {
            "rank": worker.rank,
            "world_size": world_size,
            "mesh": mesh_descriptor(mesh),
            "history": history,
            "final_loss": history["total_loss_train"][-1],
        }

    return run_workers(world_size, worker_fn)


# ------------------------------------------------------------ proxy rendezvous
class ProxyRendezvous:
    """The spawn-path rendezvous: the same named-barrier/allgather protocol
    over a localhost TCP socket, for workers that are separate OS processes.

    Rank 0 hosts the coordinator (``serve()``); every rank (0 included)
    connects a client. One round = every rank POSTs ``(tag, rank, payload)``
    and blocks until the coordinator has all ``world_size`` payloads, then
    receives the rank-ordered list — a barrier with data. Payloads are JSON
    (host metadata, shapes, health), NOT tensors: this coordinates hosts;
    device collectives still ride the backend (which is exactly why the
    spawned 2-process arm keeps its precise skip on CPU — see
    docs/DISTRIBUTED.md "Harness modes")."""

    def __init__(self, world_size: int, timeout_s: float = _BARRIER_TIMEOUT_S):
        self.world_size = int(world_size)
        self.timeout_s = float(timeout_s)
        self._server = None
        # One-way mailbox (the TCP twin of LoopbackRendezvous.post):
        # heartbeats and membership announcements from spawned workers,
        # drained by the supervisor's membership loop. Written by coordinator
        # handler threads, read by the supervisor.
        self._mail_lock = tsan.instrument_lock(
            threading.Lock(), "ProxyRendezvous._mail_lock"
        )
        self._mailbox: dict = {}  # guarded-by: self._mail_lock

    # ------------------------------------------------------------ coordinator
    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start the coordinator (rank 0's process); returns the bound port."""
        import socketserver

        world = self.world_size
        lock = tsan.instrument_lock(threading.Lock(), "ProxyRendezvous._lock")
        # tag -> [generation, ...]; each generation is one round
        # ({"slots": {rank: payload}, "done": Event, "served": count}). Tags
        # are REUSABLE across rounds (a heartbeat loop barriers on the same
        # name forever): a post onto a completed generation starts a fresh
        # one, and a generation is evicted once every rank has received its
        # result — no stale payloads, no unbounded coordinator growth. The
        # client protocol guarantees no rank re-posts a tag before its
        # previous call returned (allgather blocks until the round is full),
        # so at most the newest generation is incomplete.
        rounds: dict = {}  # guarded-by: lock

        proxy = self

        class Handler(socketserver.StreamRequestHandler):
            timeout = self.timeout_s  # per-connection read deadline

            def handle(self) -> None:
                line = self.rfile.readline()
                if not line:
                    return
                if not line.endswith(b"\n"):
                    # A torn frame (client died mid-write, or a deadline cut
                    # the read): answer loudly instead of feeding half a JSON
                    # document to the decoder.
                    self.wfile.write(
                        b'{"error": "partial frame (no trailing newline)"}\n'
                    )
                    return
                try:
                    msg = json.loads(line.decode())
                except ValueError:
                    self.wfile.write(b'{"error": "undecodable frame"}\n')
                    return
                tag, rank, payload = msg["tag"], int(msg["rank"]), msg["payload"]
                if msg.get("mode") == "post":
                    # One-way mailbox post: store and ACK immediately — a
                    # heartbeat must never block on a barrier round.
                    with proxy._mail_lock:
                        proxy._mailbox.setdefault(tag, []).append(
                            (rank, payload)
                        )
                    self.wfile.write(b'{"result": "posted"}\n')
                    return
                with lock:
                    gens = rounds.setdefault(tag, [])
                    if not gens or gens[-1]["done"].is_set():
                        gens.append(
                            {
                                "slots": {},
                                "done": threading.Event(),
                                "served": 0,
                            }
                        )
                    rnd = gens[-1]
                    if rank in rnd["slots"]:
                        self.wfile.write(
                            b'{"error": "duplicate rank post before round '
                            b'completion"}\n'
                        )
                        return
                    rnd["slots"][rank] = payload
                    if len(rnd["slots"]) == world:
                        rnd["done"].set()
                if not rnd["done"].wait(timeout=self.server.proxy.timeout_s):
                    with lock:
                        # Evict the wedged generation so the tag is not
                        # poisoned: survivors' retries must start a FRESH
                        # round instead of bouncing off their own stale
                        # slots as duplicate posts.
                        if rnd in gens and not rnd["done"].is_set():
                            gens.remove(rnd)
                            if not gens:
                                rounds.pop(tag, None)
                    self.wfile.write(b'{"error": "proxy barrier timeout"}\n')
                    return
                with lock:
                    out = [rnd["slots"].get(r) for r in range(world)]
                    rnd["served"] += 1
                    if rnd["served"] == world:
                        gens.remove(rnd)
                        if not gens:
                            rounds.pop(tag, None)
                self.wfile.write(
                    (json.dumps({"result": out}) + "\n").encode()
                )

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self._server.proxy = self
        threading.Thread(
            target=self._server.serve_forever,
            name="proxy-rendezvous",
            daemon=True,
        ).start()
        return self._server.server_address[1]

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    # ------------------------------------------------------- server-side drain
    def posts(self, tag: str = "post") -> List[tuple]:
        """Drain (and clear) the coordinator-side mailbox for ``tag`` — the
        supervisor's membership loop feeds these into a
        :class:`~hydragnn_tpu.parallel.elastic.MembershipTracker`."""
        with self._mail_lock:
            return self._mailbox.pop(tag, [])

    # ----------------------------------------------------------------- client
    @staticmethod
    def _round_trip(
        address: str,
        doc: dict,
        timeout_s: float,
        connect_retries: int = 2,
    ) -> dict:
        """One hardened request/reply frame: connect with capped-backoff
        retry (the ``DeviceFeed(transfer_retries=)`` transient-failure
        policy, applied to the wire — a coordinator still binding its socket
        must not fail the whole world), write+read under explicit deadlines,
        and a LOUD partial-frame error instead of a hang or a bare JSON
        decode crash when the peer dies mid-frame."""
        import socket
        import time as _time

        host, _, port = address.partition(":")
        what = doc.get("tag", "?")
        last_err: Optional[Exception] = None
        for attempt in range(connect_retries + 1):
            try:
                conn = socket.create_connection(
                    (host, int(port)), timeout=timeout_s
                )
                break
            except OSError as e:
                last_err = e
                if attempt >= connect_retries:
                    raise LoopbackError(
                        f"proxy rendezvous {what!r}: connect to {address} "
                        f"failed after {attempt + 1} attempt(s): {e}"
                    ) from e
                _time.sleep(min(0.05 * (2**attempt), 1.0))
        else:  # pragma: no cover - loop always breaks or raises
            raise LoopbackError(str(last_err))
        with conn as s:
            # Write AND read deadlines: a wedged coordinator surfaces as a
            # socket.timeout here, never an unbounded hang.
            s.settimeout(timeout_s)
            f = s.makefile("rwb")
            f.write((json.dumps(doc) + "\n").encode())
            f.flush()
            try:
                line = f.readline()
            except OSError as e:  # socket.timeout is an OSError subclass
                raise LoopbackError(
                    f"proxy rendezvous {what!r}: reply read from {address} "
                    f"timed out/failed after {timeout_s:g}s: {e}"
                ) from e
        if not line or not line.endswith(b"\n"):
            raise LoopbackError(
                f"proxy rendezvous {what!r}: partial frame from {address} "
                f"({len(line)} byte(s) without a trailing newline) — the "
                "coordinator died or a deadline cut the reply mid-frame"
            )
        try:
            return json.loads(line.decode())
        except ValueError as e:
            raise LoopbackError(
                f"proxy rendezvous {what!r}: undecodable reply frame from "
                f"{address}: {e}"
            ) from e

    @staticmethod
    def allgather(
        address: str, tag: str, rank: int, payload: Any,
        timeout_s: float = _BARRIER_TIMEOUT_S,
        connect_retries: int = 2,
    ) -> List[Any]:
        """Client side: post this rank's payload for ``tag``, block until all
        ranks posted, return the rank-ordered payload list."""
        reply = ProxyRendezvous._round_trip(
            address,
            {"tag": tag, "rank": rank, "payload": payload},
            timeout_s,
            connect_retries=connect_retries,
        )
        if "error" in reply:
            raise LoopbackError(f"proxy rendezvous {tag!r}: {reply['error']}")
        return reply["result"]

    @staticmethod
    def post(
        address: str, tag: str, rank: int, payload: Any,
        timeout_s: float = _POST_TIMEOUT_S,
        connect_retries: int = 2,
    ) -> None:
        """One-way mailbox post (heartbeats, membership announcements):
        ACKed by the coordinator immediately, never blocks on a barrier."""
        reply = ProxyRendezvous._round_trip(
            address,
            {"tag": tag, "rank": rank, "payload": payload, "mode": "post"},
            timeout_s,
            connect_retries=connect_retries,
        )
        if "error" in reply:
            raise LoopbackError(f"proxy rendezvous {tag!r}: {reply['error']}")

    @staticmethod
    def barrier(
        address: str, name: str, rank: int,
        timeout_s: float = _BARRIER_TIMEOUT_S,
    ) -> None:
        ProxyRendezvous.allgather(
            address, f"barrier:{name}", rank, None, timeout_s=timeout_s
        )


# --------------------------------------------------------------------- CLI
def main(argv: Optional[Sequence[str]] = None) -> int:
    """Loopback DP e2e from a JSON config — the run_suite_2proc fallback arm
    and the CI 4-device smoke. Prints one ``FINAL_LOSS <rank> <loss>`` line
    per worker (all must agree — psum-reduced) and a summary JSON."""
    import argparse
    import os

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("config")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--epochs", type=int, default=None)
    ap.add_argument("--grad-sync", default=None)
    ap.add_argument(
        "--thresholds",
        default=None,
        help='"rmse" convergence gate on the final reduced train loss',
    )
    args = ap.parse_args(argv)

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={max(args.workers, 2)}"
    )
    import jax

    # Same accelerator opt-in as benchmarks/: HYDRAGNN_TPU_TESTS=1 leaves
    # the real backend so the harness can drive actual devices; default is
    # the hermetic virtual CPU topology pinned above.
    if os.environ.get("HYDRAGNN_TPU_TESTS") != "1":
        jax.config.update("jax_platforms", "cpu")
    with open(args.config) as f:
        config = json.load(f)
    results = loopback_train(
        config,
        world_size=args.workers,
        num_epochs=args.epochs,
        grad_sync=args.grad_sync,
    )
    for r in results:
        print(f"FINAL_LOSS {r['rank']} {r['final_loss']:.10f}", flush=True)
    finals = {r["final_loss"] for r in results}
    ok = len(finals) == 1
    if args.thresholds is not None:
        bound = float(args.thresholds.split()[0])
        ok = ok and all(r["final_loss"] < bound for r in results)
    print(
        json.dumps(
            {
                "mode": "loopback",
                "workers": args.workers,
                "mesh": results[0]["mesh"],
                "final_loss": results[0]["final_loss"],
                "workers_agree": len(finals) == 1,
                "ok": ok,
            }
        )
    )
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
