from .distributed import (
    barrier,
    get_comm_size_and_rank,
    get_local_rank,
    get_local_size,
    init_comm_size_and_rank,
    make_mesh,
    mesh_descriptor,
    parse_slurm_nodelist,
    resolve_coordinator_address,
    setup_ddp,
)
from .elastic import (
    ElasticConfig,
    ElasticError,
    ElasticEvent,
    ElasticSchedule,
    ElasticTrainer,
    MembershipChange,
    MembershipTracker,
    TransitionKilled,
    WorkerKilled,
    check_restart_topology,
    shard_schedule,
    shard_window,
)
from .loopback import (
    LoopbackError,
    LoopbackRendezvous,
    LoopbackWorker,
    ProxyRendezvous,
    loopback_train,
    run_workers,
)
from .overlap import (
    GRAD_SYNC_MODES,
    overlap_fraction,
    plan_buckets,
    resolve_grad_sync,
    ring_psum,
)
