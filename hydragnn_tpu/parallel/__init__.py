from .distributed import (
    barrier,
    get_comm_size_and_rank,
    get_local_rank,
    get_local_size,
    init_comm_size_and_rank,
    make_mesh,
    parse_slurm_nodelist,
    resolve_coordinator_address,
    setup_ddp,
)
