from .distributed import (
    barrier,
    get_comm_size_and_rank,
    init_comm_size_and_rank,
    make_mesh,
    setup_ddp,
)
