"""Distributed runtime — XLA-collective replacement for the reference's
torch.distributed/NCCL layer (/root/reference/hydragnn/utils/distributed.py).

The reference wires DDP over NCCL/Gloo with env-var rendezvous (OpenMPI/SLURM/LSF)
and wraps the model (distributed.py:110-226). Here the distribution contract is the
pjit/shard_map train step itself (SURVEY.md §7 pillar 2): this module only owns
process bootstrap (jax.distributed), the device mesh, host barriers, and rank
helpers. There is no model wrapper — gradient allreduce is a psum inside the
compiled step, riding ICI/DCN.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np


def init_comm_size_and_rank() -> Tuple[int, int]:
    """World size / rank from the same scheduler env the reference parses
    (OpenMPI, SLURM — distributed.py:77-94), else single process."""
    world_size, world_rank = 1, 0
    if os.getenv("OMPI_COMM_WORLD_SIZE") and os.getenv("OMPI_COMM_WORLD_RANK"):
        world_size = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        world_rank = int(os.environ["OMPI_COMM_WORLD_RANK"])
    elif os.getenv("SLURM_NPROCS") and os.getenv("SLURM_PROCID"):
        world_size = int(os.environ["SLURM_NPROCS"])
        world_rank = int(os.environ["SLURM_PROCID"])
    return world_size, world_rank


def _distributed_active() -> bool:
    """Whether jax.distributed.initialize already ran — checked WITHOUT
    touching jax.process_count(), which would initialize the XLA backend and
    make a later initialize() impossible."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def setup_ddp(coordinator_address: Optional[str] = None) -> Tuple[int, int]:
    """Process-group bootstrap (reference setup_ddp, distributed.py:110-158).

    Multi-process: jax.distributed.initialize with scheduler-env rendezvous.
    Single-process (or rendezvous env missing): sequential fallback, like the
    reference's try/except (distributed.py:134-157).
    """
    world_size, world_rank = init_comm_size_and_rank()
    if world_size > 1 and not _distributed_active():
        try:
            if coordinator_address is None:
                master_addr = os.getenv("MASTER_ADDR", "127.0.0.1")
                master_port = os.getenv("MASTER_PORT", "8889")
                coordinator_address = f"{master_addr}:{master_port}"
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=world_size,
                process_id=world_rank,
            )
        except Exception as e:  # sequential fallback (distributed.py:155-157)
            print(f"Fall back to sequential execution mode: {e}")
            return 1, 0
    return get_comm_size_and_rank()


def get_comm_size_and_rank() -> Tuple[int, int]:
    return jax.process_count(), jax.process_index()


def barrier(name: str = "hydragnn_barrier") -> None:
    """Host-level barrier (reference dist.barrier around data prep/log dirs)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def get_device_list():
    return jax.local_devices()


def make_mesh(
    data_axis: Optional[int] = None,
    graph_axis: int = 1,
    devices=None,
) -> jax.sharding.Mesh:
    """Device mesh for the train step: 'data' (batch/DP) × 'graph'
    (intra-graph node/edge sharding — the long-context analog axis).

    ``devices``: explicit device list (e.g. ``jax.devices("cpu")`` to build a
    virtual CPU mesh on a TPU-attached host); defaults to ``jax.devices()``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if graph_axis < 1 or graph_axis > n:
        raise ValueError(
            f"graph_axis={graph_axis} must be in [1, {n}] (device count)"
        )
    if data_axis is None:
        if n % graph_axis != 0:
            raise ValueError(
                f"device count {n} is not divisible by graph_axis={graph_axis}; "
                "pass data_axis explicitly to use a subset of devices"
            )
        data_axis = n // graph_axis
    if data_axis * graph_axis > n:
        raise ValueError(
            f"mesh {data_axis}x{graph_axis} needs {data_axis * graph_axis} "
            f"devices but only {n} are available"
        )
    grid = np.asarray(devices[: data_axis * graph_axis]).reshape(
        data_axis, graph_axis
    )
    return jax.sharding.Mesh(grid, ("data", "graph"))
