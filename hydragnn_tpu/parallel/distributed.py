"""Distributed runtime — XLA-collective replacement for the reference's
torch.distributed/NCCL layer (/root/reference/hydragnn/utils/distributed.py).

The reference wires DDP over NCCL/Gloo with env-var rendezvous (OpenMPI/SLURM/LSF)
and wraps the model (distributed.py:110-226). Here the distribution contract is the
pjit/shard_map train step itself (SURVEY.md §7 pillar 2): this module only owns
process bootstrap (jax.distributed), the device mesh, host barriers, and rank
helpers. There is no model wrapper — gradient allreduce is a psum inside the
compiled step, riding ICI/DCN.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np


def init_comm_size_and_rank() -> Tuple[int, int]:
    """World size / rank from the same scheduler env the reference parses
    (OpenMPI, SLURM — distributed.py:77-94), else single process."""
    world_size, world_rank = 1, 0
    if os.getenv("OMPI_COMM_WORLD_SIZE") and os.getenv("OMPI_COMM_WORLD_RANK"):
        world_size = int(os.environ["OMPI_COMM_WORLD_SIZE"])
        world_rank = int(os.environ["OMPI_COMM_WORLD_RANK"])
    elif os.getenv("SLURM_NPROCS") and os.getenv("SLURM_PROCID"):
        world_size = int(os.environ["SLURM_NPROCS"])
        world_rank = int(os.environ["SLURM_PROCID"])
    return world_size, world_rank


def parse_slurm_nodelist(nodelist: str) -> list:
    """Expand a SLURM compressed hostlist into individual node names — the
    rendezvous-address source on SLURM clusters (reference
    /root/reference/hydragnn/utils/distributed.py:43-74, used at :126-132).

    Handles single nodes, bracketed groups, zero-padded ranges, and multiple
    comma-separated blocks: ``"gpu-a,node[01,03-05]"`` →
    ``["gpu-a", "node01", "node03", "node04", "node05"]``.
    """
    # Split on commas OUTSIDE brackets only.
    blocks, depth, cur = [], 0, []
    for ch in nodelist:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth -= 1
        if ch == "," and depth == 0:
            blocks.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        blocks.append("".join(cur))

    nodes = []
    for block in blocks:
        block = block.strip()
        if block:
            nodes.extend(_expand_hostlist_block(block))
    return nodes


def _expand_hostlist_block(block: str) -> list:
    """Expand ONE hostlist block, recursing past the first bracket group so
    multi-dimension names ("rack[1-2]n[1-4]") and suffixes ("tux[1-2]-ib")
    expand instead of crashing."""
    i = block.find("[")
    if i < 0:
        return [block]
    j = block.index("]", i)
    prefix, group, rest = block[:i], block[i + 1 : j], block[j + 1 :]
    tails = _expand_hostlist_block(rest) if rest else [""]
    out = []
    for item in group.split(","):
        lo, _, hi = item.partition("-")
        if hi:
            width = len(lo)
            mids = [f"{k:0{width}d}" for k in range(int(lo), int(hi) + 1)]
        else:
            mids = [item]
        out.extend(prefix + mid + tail for mid in mids for tail in tails)
    return out


def resolve_coordinator_address() -> str:
    """Coordinator (rendezvous master) address, resolved the way the reference
    picks MASTER_ADDR (distributed.py:120-132): explicit env wins, then the
    LSF batch hostlist (first compute host — LSB_HOSTS[0] is the batch node),
    then the first SLURM node, else localhost. Port from MASTER_PORT or the
    reference's default 8889."""
    addr = os.getenv("MASTER_ADDR")
    if not addr and os.getenv("LSB_HOSTS"):
        hosts = os.environ["LSB_HOSTS"].split()
        addr = hosts[1] if len(hosts) > 1 else hosts[0]
    if not addr and os.getenv("SLURM_NODELIST"):
        nodes = parse_slurm_nodelist(os.environ["SLURM_NODELIST"])
        addr = nodes[0] if nodes else None
    if not addr:
        addr = "127.0.0.1"
    return f"{addr}:{os.getenv('MASTER_PORT', '8889')}"


def get_local_rank() -> int:
    """Process index within its host (reference local-rank selection,
    distributed.py:181-189) — picks this process's slot among the host's local
    devices in multi-process-per-host launches."""
    fam = _local_family()
    if fam is not None:  # a complete rank+size family wins over a lone var
        return fam[0]
    for var in ("OMPI_COMM_WORLD_LOCAL_RANK", "SLURM_LOCALID"):
        if os.getenv(var):
            return int(os.environ[var])
    return 0


def _tasks_per_node_counts(val: str) -> list:
    """Per-node task counts from SLURM_NTASKS_PER_NODE's compressed grammar:
    "4" → [4]; "4(x2)" → [4, 4]; "4(x2),3" → [4, 4, 3] (heterogeneous)."""
    counts = []
    for part in val.split(","):
        n, _, rep = part.partition("(x")
        counts.extend([int(n)] * (int(rep.rstrip(")")) if rep else 1))
    return counts


def _local_family():
    """(local_rank, max tasks-per-node) read from ONE launcher family — mixing
    (e.g. SLURM size with an OMPI rank) silently misplaces processes. None if
    no family is fully present or its size grammar doesn't parse."""
    for rank_var, size_var in (
        ("OMPI_COMM_WORLD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_SIZE"),
        ("SLURM_LOCALID", "SLURM_NTASKS_PER_NODE"),
    ):
        if os.getenv(rank_var) and os.getenv(size_var):
            try:
                counts = _tasks_per_node_counts(os.environ[size_var])
                return int(os.environ[rank_var]), max(counts)
            except ValueError:
                return None
    return None


def get_local_size() -> int:
    """Processes launched per host — the max over nodes on heterogeneous
    allocations (1 when the scheduler doesn't say or the value is garbled)."""
    fam = _local_family()
    if fam is not None:
        return fam[1]
    for var in ("OMPI_COMM_WORLD_LOCAL_SIZE", "SLURM_NTASKS_PER_NODE"):
        if os.getenv(var):
            try:
                return max(_tasks_per_node_counts(os.environ[var]))
            except ValueError:
                return 1
    return 1


def _local_device_slot():
    """Local-device slot for this process, or None for JAX's default (claim
    all local devices). Slot mode only when the launcher says several
    processes share a host (local rank > 0 is itself proof)."""
    fam = _local_family()
    if fam is not None and (fam[0] > 0 or fam[1] > 1):
        return fam[0]
    return None


def _distributed_active() -> bool:
    """Whether jax.distributed.initialize already ran — checked WITHOUT
    touching jax.process_count(), which would initialize the XLA backend and
    make a later initialize() impossible."""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def setup_ddp(coordinator_address: Optional[str] = None) -> Tuple[int, int]:
    """Process-group bootstrap (reference setup_ddp, distributed.py:110-158).

    Multi-process: jax.distributed.initialize with scheduler-env rendezvous.
    Single-process (or rendezvous env missing): sequential fallback, like the
    reference's try/except (distributed.py:134-157).
    """
    world_size, world_rank = init_comm_size_and_rank()
    if world_size > 1 and not _distributed_active():
        try:
            if coordinator_address is None:
                coordinator_address = resolve_coordinator_address()
            kwargs = {}
            slot = _local_device_slot()
            if slot is not None:
                # Reference 1-rank-per-device placement (distributed.py:
                # 181-189): with several processes per host each claims its
                # own local-device slot instead of all of them.
                kwargs["local_device_ids"] = [slot]
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=world_size,
                process_id=world_rank,
                **kwargs,
            )
        except Exception as e:
            # DIVERGENCE from the reference's silent sequential fallback
            # (distributed.py:155-157): once the scheduler env promised
            # world_size > 1, peers are already connecting to the coordinator
            # — one rank quietly going sequential leaves the rest blocked at
            # rendezvous until timeout. Fail loudly instead.
            raise RuntimeError(
                f"jax.distributed.initialize failed for rank {world_rank}/"
                f"{world_size} at {coordinator_address}: {e}. Check the "
                "rendezvous env (MASTER_ADDR/LSB_HOSTS/SLURM_NODELIST) and "
                "that the local device slot exists on this host."
            ) from e
    return get_comm_size_and_rank()


def get_comm_size_and_rank() -> Tuple[int, int]:
    return jax.process_count(), jax.process_index()


def barrier(name: str = "hydragnn_barrier") -> None:
    """Host-level barrier (reference dist.barrier around data prep/log dirs)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def get_device_list():
    return jax.local_devices()


def mesh_descriptor(mesh) -> str:
    """Canonical axis-layout string of a mesh — ``"data:4xgraph:2"``. The
    graftmesh CacheKey component (docs/COMPILE_CACHE.md): two shard_map
    programs over different axis factorizations of the SAME device count
    compile different collectives and must never hydrate each other."""
    return "x".join(f"{name}:{int(size)}" for name, size in mesh.shape.items())


def config_graph_axis(config: dict) -> int:
    """The JSON config's edge-sharding request — ``Training.graph_axis``
    (>1 shards each graph's edges over that many devices; absent/falsy means
    1). ONE definition consumed by run_training AND run_prediction so the
    same config can never build different meshes for the two."""
    return int(
        config.get("NeuralNetwork", {}).get("Training", {}).get("graph_axis", 1)
        or 1
    )


def make_mesh(
    data_axis: Optional[int] = None,
    graph_axis: int = 1,
    devices=None,
) -> jax.sharding.Mesh:
    """Device mesh for the train step: 'data' (batch/DP) × 'graph'
    (intra-graph node/edge sharding — the long-context analog axis).

    ``devices``: explicit device list (e.g. ``jax.devices("cpu")`` to build a
    virtual CPU mesh on a TPU-attached host); defaults to ``jax.devices()``.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if graph_axis < 1 or graph_axis > n:
        raise ValueError(
            f"graph_axis={graph_axis} must be in [1, {n}] (device count)"
        )
    if data_axis is None:
        if n % graph_axis != 0:
            raise ValueError(
                f"device count {n} is not divisible by graph_axis={graph_axis}; "
                "pass data_axis explicitly to use a subset of devices"
            )
        data_axis = n // graph_axis
    if data_axis * graph_axis > n:
        raise ValueError(
            f"mesh {data_axis}x{graph_axis} needs {data_axis * graph_axis} "
            f"devices but only {n} are available"
        )
    grid = np.asarray(devices[: data_axis * graph_axis]).reshape(
        data_axis, graph_axis
    )
    return jax.sharding.Mesh(grid, ("data", "graph"))
