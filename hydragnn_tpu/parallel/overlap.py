"""graftmesh gradient-sync arms — bucketed all-reduce overlapped with
backward compute, and a ppermute-ring alternative (docs/DISTRIBUTED.md).

The single-psum DP step (train/trainer.make_train_step_dp, the DDP-allreduce
analog) reduces the WHOLE gradient tree after the full backward: XLA sees one
psum that depends on every cotangent, so no collective can start until the
last backward op retires. This module restructures the dataflow so each
gradient BUCKET's all-reduce depends only on that bucket's cotangents:

* ``plan_buckets`` partitions the param leaves into size-targeted buckets in
  REVERSE flatten order — parameters consumed late in the forward (output
  heads) produce their cotangents FIRST in the backward, so the first bucket's
  reduce can dispatch while the conv stack's backward is still running.
* ``attach_grad_sync`` threads the params through per-bucket ``custom_vjp``
  identities whose backward performs the reduce. The forward is untouched
  (identity); in the backward graph each bucket's collective is a separate op
  whose operands are exactly that bucket's cotangents — XLA's latency-hiding
  scheduler is then FREE to overlap it with the remaining backward compute
  (async collectives on TPU; on CPU the ops serialize, which is why
  MULTICHIP artifacts label CPU overlap fractions non-meaningful).
* ``ring_psum`` is the ppermute-ring arm: the same bucket hook, but the
  reduce is an explicit (axis_size - 1)-step rotate-and-accumulate ring —
  the hand-scheduled alternative A/B'd against the compiler-scheduled psum
  (bench.py --multichip).

Weighting contract: the callers multiply each shard's LOCAL loss by
``count / max(psum(count), 1)`` before differentiation, so the plain SUM the
bucket reduce computes equals the single-psum arm's graph-count-weighted
mean gradient exactly (the weight is constant w.r.t. params) — the arms are
allclose by construction, locked by tests/test_graftmesh.py.

Everything here is traced inside the shard_map step: no host state, no wall
clock, no global RNG.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

GRAD_SYNC_MODES = ("single", "bucketed", "ring")
DEFAULT_BUCKET_MB = 4.0


def resolve_grad_sync(value) -> str:
    """Validate a ``Training.grad_sync`` knob (None → the single-psum arm).
    The runtime twin of the contract checker's ``bad-mesh`` finding."""
    if value in (None, ""):
        return "single"
    if value not in GRAD_SYNC_MODES:
        raise ValueError(
            f"grad_sync {value!r} is not one of {GRAD_SYNC_MODES}"
        )
    return str(value)


def plan_buckets(params: Any, bucket_bytes: float) -> List[Tuple[int, ...]]:
    """Partition the param tree's flat leaves into size-targeted buckets.

    Leaves are walked in REVERSE flatten order (flax flatten order follows
    module definition order, which follows forward execution order — its
    reverse approximates backward cotangent availability). Greedy fill: a
    bucket closes when adding the next leaf would exceed ``bucket_bytes``;
    single leaves larger than the target get their own bucket. Derived from
    static shapes/dtypes only, so the plan is a trace-time constant."""
    leaves = jax.tree_util.tree_leaves(params)
    bucket_bytes = max(float(bucket_bytes), 1.0)
    buckets: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_bytes = 0.0
    for i in reversed(range(len(leaves))):
        leaf = leaves[i]
        nbytes = float(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0.0
        cur.append(i)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))
    return buckets


def ring_psum(tree: Any, axis_name: str, axis_size: int) -> Any:
    """Explicit ring all-reduce: ``axis_size - 1`` rotate-and-accumulate
    ppermute steps. Same value as ``lax.psum`` up to f32 summation order
    (each shard accumulates the ring in ITS OWN rotation order), which is why
    the equivalence gate is allclose, not bitwise. ``axis_size`` must be the
    static mesh axis size (ppermute permutations are trace-time constants)."""
    if axis_size <= 1:
        return tree
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
    acc, cur = tree, tree
    for _ in range(axis_size - 1):
        cur = jax.tree_util.tree_map(
            lambda x: jax.lax.ppermute(x, axis_name, perm), cur
        )
        acc = jax.tree_util.tree_map(jnp.add, acc, cur)
    return acc


def make_reduce(
    grad_sync: str, grad_axes: Sequence[str], data_axis_size: int
) -> Callable[[Any], Any]:
    """The per-bucket reduce for :func:`attach_grad_sync`: psum (or ring
    all-reduce) over 'data', then pmean over 'graph' when the mesh has a
    nontrivial graph axis (edge-shard contributions are means over the
    replicated node params — the same composition the single-psum arm
    applies after the full backward)."""
    graph = "graph" in grad_axes

    def reduce_fn(cots: Any) -> Any:
        if grad_sync == "ring":
            out = ring_psum(cots, "data", data_axis_size)
        else:
            # One psum bind over the bucket's tuple → one variadic
            # all-reduce op whose operands are exactly this bucket.
            out = jax.lax.psum(cots, "data")
        if graph:
            out = jax.lax.pmean(out, "graph")
        return out

    return reduce_fn


def _make_bucket_sync(reduce_fn: Callable[[Any], Any]):
    """Identity-forward / reduce-backward hook for ONE bucket. The primal is
    the tuple of the bucket's param leaves; the backward reduces the tuple of
    cotangents in one collective."""

    @jax.custom_vjp
    def sync(leaves):
        return leaves

    def fwd(leaves):
        return leaves, None

    def bwd(_, cots):
        return (reduce_fn(cots),)

    sync.defvjp(fwd, bwd)
    return sync


def attach_grad_sync(
    params: Any,
    plan: Sequence[Tuple[int, ...]],
    reduce_fn: Callable[[Any], Any],
) -> Any:
    """Thread ``params`` through the per-bucket sync hooks. Forward math is
    untouched; gradients come back ALREADY reduced, bucket by bucket, at the
    point in the backward graph where each bucket's cotangents finalize."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out = list(leaves)
    for bucket in plan:
        sync = _make_bucket_sync(reduce_fn)
        synced = sync(tuple(out[i] for i in bucket))
        for j, i in enumerate(bucket):
            out[i] = synced[j]
    return jax.tree_util.tree_unflatten(treedef, out)


def overlap_fraction(
    t_single: float, t_overlapped: float, t_nosync: float
) -> "float | None":
    """Fraction of the gradient all-reduce wall hidden behind backward
    compute, from three steady step times: the single-psum arm, the
    overlapped arm, and a no-sync lower bound (local step, no collectives).
    ``(t_single - t_overlapped) / (t_single - t_nosync)``, clamped to [0, 1];
    None when the collective share is too small to measure (denominator
    within noise of zero)."""
    denom = t_single - t_nosync
    if denom <= 1e-9 or not all(
        x > 0 for x in (t_single, t_overlapped, t_nosync)
    ):
        return None
    return max(0.0, min(1.0, (t_single - t_overlapped) / denom))
