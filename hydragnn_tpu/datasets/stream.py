"""graftstream — the out-of-core streaming loader over GSHD shards
(docs/DATA_PLANE.md).

Three pieces:

* :func:`plan_shard_ring` — a pure function turning one epoch's batch plan
  into (decode order, eviction schedule) under a resident-shard capacity.
  Eviction is Belady (farthest next use), so an unshuffled epoch streams one
  shard at a time while a globally-shuffled epoch trades bounded re-decodes
  for bounded RAM — correctness never depends on the capacity.
* :class:`ShardRing` — the bounded decode-ahead ring: a named daemon thread
  ("hydragnn-shard-prefetch", registered in
  ``analysis.rules.THREAD_CALLABLE_BINDINGS``) walks the decode order and
  feeds verified shards through a bounded queue. A corrupt shard is
  delivered as a (sid, None, reason) item — the consumer quarantines it; the
  thread never dies on data corruption.
* :class:`StreamingGraphLoader` — a ``GraphDataLoader`` whose corpus lives
  on disk. The epoch plan is the INHERITED one, computed from the GSHD index
  (per-sample node/edge counts) alone, and every knob — ``num_shards``/
  ``shard_rank`` dealing, buckets, packing, reshuffle — behaves identically:
  streamed training is bit-exact vs the in-memory loader at matched
  seed/shapes (tests/test_stream.py pins collation parity and the elastic
  sample-conservation contract). Under the training ``DeviceFeed`` this
  iterator runs on the feed-host thread, so shard I/O + decode (ring
  thread) overlaps collation (feed-host) overlaps H2D (feed-transfer)
  overlaps device compute.
"""

from __future__ import annotations

import bisect
import os
import queue
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..analysis import tsan
from ..graphs.collate import GraphArena
from ..graphs.packing import SizeHistogram
from ..graphs.sample import GraphSample
from ..preprocess.dataloader import GraphDataLoader
from . import shards as gshd


def plan_shard_ring(
    needs: Sequence[Sequence[int]], capacity: int
) -> Tuple[List[int], List[List[int]]]:
    """Fetch/evict schedule for one epoch: ``needs[k]`` is the ordered list
    of distinct shard ids batch ``k`` touches. Returns ``(fetch_seq,
    evict_after)`` — the exact order the ring thread decodes shards, and the
    shards the consumer drops after each batch. Pure function (the consumer
    and the ring replay the same schedule without sharing mutable state).

    A shard evicted under capacity pressure and needed again later simply
    re-enters ``fetch_seq`` — bounded memory costs a re-decode, never
    correctness. Eviction picks the resident shard with the farthest next
    use (Belady-optimal for a known access sequence); shards never needed
    again are always dropped first."""
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    uses: Dict[int, List[int]] = {}
    for pos, sids in enumerate(needs):
        for sid in sids:
            uses.setdefault(sid, []).append(pos)
    fetch_seq: List[int] = []
    evict_after: List[List[int]] = []
    resident: set = set()
    for pos, sids in enumerate(needs):
        for sid in sids:
            if sid not in resident:
                fetch_seq.append(sid)
                resident.add(sid)
        evictions = [
            sid
            for sid in sorted(resident)
            if bisect.bisect_right(uses[sid], pos) >= len(uses[sid])
        ]
        resident.difference_update(evictions)
        while len(resident) > capacity:
            far = max(
                resident,
                key=lambda sid, pos=pos: (
                    uses[sid][bisect.bisect_right(uses[sid], pos)],
                    sid,
                ),
            )
            resident.discard(far)
            evictions.append(far)
        evict_after.append(sorted(evictions))
    return fetch_seq, evict_after


class ShardRing:
    """Bounded decode-ahead ring of shards on a named daemon thread.

    ``decode(sid)`` runs on the "hydragnn-shard-prefetch" thread
    (``rules.THREAD_CALLABLE_BINDINGS``) and must return ``(payload,
    nbytes)``; a :class:`..checkpoint.format.CheckpointCorruptError` from it
    becomes a ``(sid, None, reason)`` item so the consumer can quarantine
    the shard without losing the run. Any OTHER exception re-raises at the
    consumer, exactly like the training ``_Prefetcher``. The queue depth
    bounds decode-ahead; abandoning consumption (``close``) cancels the
    thread so neither it nor decoded shards leak."""

    _SENTINEL = object()

    def __init__(
        self, fetch_seq: Sequence[int], decode: Callable, depth: int = 2
    ):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(depth)))
        self._cancel = threading.Event()
        self._err: Optional[BaseException] = None
        self._lock = tsan.instrument_lock(threading.Lock(), "ShardRing._lock")
        with self._lock:
            self.shards_decoded = 0  # guarded-by: self._lock
            self.shards_failed = 0  # guarded-by: self._lock
            self.bytes_decoded = 0  # guarded-by: self._lock

        def _run():
            try:
                for sid in fetch_seq:
                    if self._cancel.is_set():
                        return
                    item = self._decode_one(sid, decode)
                    tsan.yield_point("stream.ring.pre_put")
                    while not self._cancel.is_set():
                        try:
                            self._q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if self._cancel.is_set():
                        return
            except BaseException as e:  # re-raised at the consumer
                self._err = e
            finally:
                # Sentinel must not be dropped (see _Prefetcher): block with
                # cancel checks so a full queue cannot strand the consumer.
                while not self._cancel.is_set():
                    try:
                        self._q.put(self._SENTINEL, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        self._thread = threading.Thread(
            target=_run, name="hydragnn-shard-prefetch", daemon=True
        )
        self._thread.start()

    def _decode_one(self, sid: int, decode: Callable):
        from ..checkpoint.format import CheckpointCorruptError

        try:
            payload, nbytes = decode(sid)
        except CheckpointCorruptError as e:
            with self._lock:
                self.shards_failed += 1
                tsan.shared_access("ShardRing.stats")
            return (sid, None, e.reason)
        with self._lock:
            self.shards_decoded += 1
            self.bytes_decoded += int(nbytes)
            tsan.shared_access("ShardRing.stats")
        return (sid, payload, None)

    def get(self):
        """Next ``(sid, payload, reason)`` in fetch order; ``None`` when the
        fetch sequence is exhausted. Re-raises a ring-thread failure."""
        item = self._q.get()
        if item is self._SENTINEL:
            if self._err is not None:
                raise self._err
            return None
        return item

    def stats(self) -> dict:
        with self._lock:
            tsan.shared_access("ShardRing.stats")
            return {
                "shards_decoded": self.shards_decoded,
                "shards_failed": self.shards_failed,
                "bytes_decoded": self.bytes_decoded,
            }

    def close(self) -> None:
        self._cancel.set()
        # Drain so a producer blocked on put() wakes and exits.
        try:
            while True:
                self._q.get_nowait()
        except Exception:
            pass

    def join(self, timeout: float = 5.0) -> bool:
        self._thread.join(timeout)
        return not self._thread.is_alive()


class _DecodedShard:
    """One resident decoded shard: its samples, its base global index, and a
    lazily-built arena (constructed by the consumer on first single-shard
    batch — the fast collation path)."""

    __slots__ = ("samples", "base", "_arena")

    def __init__(self, samples: List[GraphSample], base: int):
        self.samples = samples
        self.base = base
        self._arena: Optional[GraphArena] = None

    @property
    def arena(self) -> GraphArena:
        if self._arena is None:
            self._arena = GraphArena(self.samples)
        return self._arena


class _CorpusView:
    """Sequence-style view over the on-disk corpus for the config-completion
    and visualization paths (``loader.dataset[0]``, ``for s in
    loader.dataset``). Sequential iteration decodes one shard at a time;
    random access keeps a one-shard cache. Never used on the training hot
    path — batches come through the shard ring."""

    def __init__(self, loader: "StreamingGraphLoader"):
        self._loader = loader

    def __len__(self) -> int:
        return int(self._loader._ns.size)

    def __iter__(self):
        manifest = self._loader.manifest
        for sh in manifest["shards"]:
            yield from gshd.load_shard(
                os.path.join(manifest["_dir"], sh["file"])
            )

    def __getitem__(self, i: int) -> GraphSample:
        n = len(self)
        i = int(i)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        return self._loader._sample_at(i)


class StreamingGraphLoader(GraphDataLoader):
    """``GraphDataLoader`` over an on-disk GSHD corpus (docs/DATA_PLANE.md).

    The corpus never materializes in host RAM: only the index (16
    bytes/sample), at most ``resident_shards`` decoded shards (+
    ``ring_depth`` decode-ahead), and the batch being collated are resident.
    The epoch plan — shuffling, ``num_shards``/``shard_rank`` round-robin
    dealing, quantile buckets, FFD packing, reshuffle granularity — is the
    inherited implementation computed over the index, so streamed training
    is bit-exact vs the in-memory loader at matched seed/shapes, and
    graftmesh's rank views / graftelastic's ``shard_schedule`` consume the
    same dealing contract unchanged.

    Quarantine is SHARD-granular: a corrupt shard (flipped byte, torn file,
    swapped content — anything v2 digest verification rejects) is dropped
    into ``self.quarantined`` up to ``skip_budget`` shards, loudly; its
    samples are skipped for the run. Exceeding the budget fails with the
    quarantine log, mirroring the in-memory sample quarantine."""

    def __init__(
        self,
        manifest_path: str,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        num_shards: int = 1,
        shard_rank: int = 0,
        head_types: Optional[Sequence[str]] = None,
        head_dims: Optional[Sequence[int]] = None,
        edge_dim: Optional[int] = None,
        num_buckets: int = 1,
        reshuffle: str = "sample",
        skip_budget: int = 0,
        packing: bool = False,
        ladder_step: str = "pow2",
        ring_depth: int = 2,
        resident_shards: int = 8,
    ):
        if reshuffle not in ("sample", "batch"):
            raise ValueError(
                f"reshuffle must be 'sample' or 'batch', got {reshuffle!r}"
            )
        self.manifest = gshd.read_manifest(manifest_path)
        self.manifest_path = gshd.manifest_path_of(manifest_path)
        self._ns, self._es = gshd.read_index(self.manifest)
        self._shard_starts = gshd.shard_offsets(self.manifest)
        self.skip_budget = int(skip_budget)
        self.quarantined: List[tuple] = []  # (shard file, reason)
        self._bad_shards: Dict[int, str] = {}
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.num_shards = num_shards
        self.shard_rank = shard_rank
        self.head_types = tuple(head_types) if head_types else None
        self.head_dims = tuple(head_dims) if head_dims else None
        if edge_dim is None:
            # Dataset-level edge width from the manifest: per-batch arenas
            # must resolve edge presence/width the way the in-memory
            # DATASET-level arena does, or a batch without edge_attr samples
            # would change the pytree structure (bit-exactness contract).
            width = int(
                (self.manifest.get("fields") or {}).get("edge_attr_width", 0)
            )
            edge_dim = width or None
        self.edge_dim = edge_dim
        self.reshuffle = reshuffle
        self.packing = bool(packing)
        self.ladder_step = ladder_step
        self.epoch = 0
        self.generation = 0
        self._arena = None
        self._frozen_plan = None
        self._plan_memo = None
        self._batch_cache: dict = {}
        self._cache_budget = int(
            os.environ.get("HYDRAGNN_HOST_CACHE_MB", "1024")
        ) * (1 << 20)
        self._cache_bytes = 0
        self.size_histogram = SizeHistogram()
        for n, e in zip(self._ns.tolist(), self._es.tolist()):
            self.size_histogram.record_graph(n, e)
        self._pad_stats = self._zero_pad_stats()
        self.ring_depth = max(1, int(ring_depth))
        self.resident_shards = max(1, int(resident_shards))
        self.dataset = _CorpusView(self)
        self._view_cache: Optional[Tuple[int, List[GraphSample]]] = None
        self._last_ring_stats: Optional[dict] = None
        # Decoded shards persisted across epochs when the epoch's shard set
        # fits the resident budget (see __iter__). Consumer-thread-only.
        self._resident: Dict[int, Optional[_DecodedShard]] = {}
        # (shard-set key, arena, per-shard merged offsets): one gather arena
        # over the warm resident set, so steady epochs collate exactly like
        # the in-memory loader (consumer-thread-only; see _iter_resident).
        self._merged: Optional[Tuple[tuple, GraphArena, np.ndarray]] = None
        self._num_buckets_requested = max(1, int(num_buckets))
        self._build_buckets(self._num_buckets_requested)

    # ------------------------------------------------------------ shard access
    def _shard_of(self, idx: np.ndarray) -> np.ndarray:
        return np.searchsorted(self._shard_starts, idx, side="right") - 1

    def _decode_shard(self, sid: int) -> Tuple[_DecodedShard, int]:
        """Read + digest-verify + decode one shard. Runs on the ring's
        shard-prefetch thread; touches no loader state."""
        from ..checkpoint.format import CheckpointCorruptError

        entry = self.manifest["shards"][int(sid)]
        path = os.path.join(self.manifest["_dir"], entry["file"])
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError as e:
            raise CheckpointCorruptError(path, f"unreadable ({e})") from e
        samples = gshd.decode_shard(blob, path)
        if len(samples) != int(entry["num_samples"]):
            raise CheckpointCorruptError(
                path,
                f"sample count {len(samples)} != manifest "
                f"{entry['num_samples']}",
            )
        base = int(self._shard_starts[int(sid)])
        return _DecodedShard(samples, base), len(blob)

    def _sample_at(self, i: int) -> GraphSample:
        sid = int(self._shard_of(np.asarray([i]))[0])
        if self._view_cache is None or self._view_cache[0] != sid:
            shard, _ = self._decode_shard(sid)
            self._view_cache = (sid, shard.samples)
        return self._view_cache[1][i - int(self._shard_starts[sid])]

    # ------------------------------------------------------------- quarantine
    def _note_bad_shard(self, sid: int, reason: str) -> None:
        """Consumer-side shard quarantine: one flipped byte costs one shard,
        loudly — and never the run while the budget holds."""
        if sid in self._bad_shards:
            return
        from ..faults.counters import FaultCounters

        entry = self.manifest["shards"][sid]
        self._bad_shards[sid] = reason
        self.quarantined.append((entry["file"], reason))
        FaultCounters.inc("quarantined_shards")
        if len(self.quarantined) > self.skip_budget:
            log = "; ".join(f"{f}: {r}" for f, r in self.quarantined[:10])
            raise RuntimeError(
                f"shard quarantine budget exceeded: {len(self.quarantined)} "
                f"corrupt shard(s) > skip_budget={self.skip_budget} — {log}"
                + (" ..." if len(self.quarantined) > 10 else "")
            )
        print(
            f"WARNING: quarantined corrupt shard {entry['file']} ({reason}); "
            f"{entry['num_samples']} sample(s) skipped for this run"
        )

    # ---------------------------------------------------------------- elastic
    def reshard(self, num_shards: int, shard_rank: int) -> None:
        """Re-deal epoch plans to a changed world (graftelastic transitions
        over an out-of-core corpus): same wrap-pad round-robin contract as
        construction, with plan memo / frozen plan / caches invalidated and
        ``generation`` bumped so external device caches detect it. The
        on-disk corpus is untouched — a world transition costs no conversion
        and no corpus scan (sample conservation: tests/test_stream.py)."""
        self.num_shards = int(num_shards)
        self.shard_rank = int(shard_rank)
        self._frozen_plan = None
        self._plan_memo = None
        self._batch_cache.clear()
        self._cache_bytes = 0
        self._merged = None
        self.generation += 1

    def ring_stats(self) -> Optional[dict]:
        """Decode counters of the most recent epoch's shard ring (bench)."""
        return self._last_ring_stats

    # -------------------------------------------------------------- iteration
    def __iter__(self):
        plan = self._batch_plan()
        if not plan:
            return
        needs: List[List[int]] = []
        order: List[int] = []
        order_set: set = set()
        for _pos, _bi, sample_idx in plan:
            sids = self._shard_of(np.asarray(sample_idx, np.int64))
            seen: List[int] = []
            seen_set: set = set()
            for sid in sids.tolist():
                if sid not in seen_set:
                    seen_set.add(sid)
                    seen.append(sid)
                if sid not in order_set:
                    order_set.add(sid)
                    order.append(sid)
            needs.append(seen)
        capacity = max(self.resident_shards, max(len(s) for s in needs))
        if len(order) <= capacity:
            # The whole epoch's shard set fits the resident budget: decoded
            # shards (and their arenas) persist across epochs, so steady
            # epochs are decode-free once warm — the out-of-core analog of
            # the in-memory loader's long-lived arena. RAM stays bounded by
            # ``capacity`` (stale shards from a previous plan are dropped).
            for sid in list(self._resident):
                if sid not in order_set:
                    del self._resident[sid]
            yield from self._iter_resident(plan, needs, order)
        else:
            # Epoch touches more shards than fit: replay the Belady
            # fetch/evict schedule; nothing persists across epochs.
            self._resident.clear()
            self._merged = None
            yield from self._iter_belady(plan, needs, capacity)

    def _iter_resident(self, plan, needs, order):
        missing = [sid for sid in order if sid not in self._resident]
        ring = (
            ShardRing(missing, self._decode_shard, depth=self.ring_depth)
            if missing
            else None
        )
        # Fully warm (steady-state epochs): gather from ONE arena over the
        # resident set — collation cost identical to the in-memory loader.
        merged = self._ensure_merged_arena(order) if ring is None else None
        try:
            for k, (pos, bi, sample_idx) in enumerate(plan):
                for sid in needs[k]:
                    if sid in self._resident:
                        continue
                    self._resident[sid] = self._next_from_ring(ring, sid)
                batch = self._emit(
                    pos,
                    bi,
                    np.asarray(sample_idx, np.int64),
                    self._resident,
                    merged=merged,
                )
                if batch is not None:
                    yield batch
        finally:
            if ring is not None:
                self._last_ring_stats = ring.stats()
                ring.close()
            else:
                self._last_ring_stats = {
                    "shards_decoded": 0,
                    "shards_failed": 0,
                    "bytes_decoded": 0,
                }

    def _ensure_merged_arena(self, order):
        """(arena, offsets) over the warm resident shard set, in global
        sample order; rebuilt only when the set (or its quarantine state)
        changes. Doubles the resident window's footprint (decoded views +
        arena concat) in exchange for in-memory-parity steady epochs."""
        key = tuple(
            sid for sid in sorted(order) if self._resident.get(sid) is not None
        )
        if self._merged is not None and self._merged[0] == key:
            return self._merged[1], self._merged[2]
        samples: List[GraphSample] = []
        offsets = np.full(len(self.manifest["shards"]), -1, np.int64)
        for sid in key:
            offsets[sid] = len(samples)
            samples.extend(self._resident[sid].samples)
        arena = GraphArena(samples)
        self._merged = (key, arena, offsets)
        return arena, offsets

    def _iter_belady(self, plan, needs, capacity):
        fetch_seq, evict_after = plan_shard_ring(needs, capacity)
        ring = ShardRing(fetch_seq, self._decode_shard, depth=self.ring_depth)
        resident: Dict[int, Optional[_DecodedShard]] = {}
        try:
            for k, (pos, bi, sample_idx) in enumerate(plan):
                for sid in needs[k]:
                    if sid in resident:
                        continue
                    resident[sid] = self._next_from_ring(ring, sid)
                batch = self._emit(
                    pos, bi, np.asarray(sample_idx, np.int64), resident
                )
                if batch is not None:
                    yield batch
                for sid in evict_after[k]:
                    resident.pop(sid, None)
        finally:
            self._last_ring_stats = ring.stats()
            ring.close()

    def _next_from_ring(self, ring, sid):
        """Pull the next scheduled shard off the ring; it MUST be ``sid``
        (consumer and ring replay the same schedule). Corrupt payloads are
        quarantined here, on the consumer thread."""
        got = ring.get() if ring is not None else None
        if got is None:
            raise RuntimeError(
                "shard ring exhausted before the plan (fetch schedule "
                "mismatch)"
            )
        gsid, payload, reason = got
        if gsid != sid:
            raise RuntimeError(
                f"shard ring out of order: wanted shard {sid}, got {gsid}"
            )
        if payload is None:
            self._note_bad_shard(sid, reason or "corrupt")
        return payload

    def _emit(self, pos, bi, sample_idx, resident, merged=None):
        """Collate one plan entry from resident shards (members of
        quarantined shards are dropped; an emptied batch is skipped)."""
        sids = self._shard_of(sample_idx)
        keep = np.fromiter(
            (resident.get(int(s)) is not None for s in sids),
            bool,
            len(sids),
        )
        if not keep.all():
            sample_idx = sample_idx[keep]
            sids = sids[keep]
        if sample_idx.size == 0:
            return None
        n_pad, e_pad, g_pad = self._bucket_pads[bi]
        tot_n = int(self._ns[sample_idx].sum())
        tot_e = int(self._es[sample_idx].sum())
        self.size_histogram.record_batch(tot_n, tot_e, len(sample_idx))
        st = self._pad_stats
        st["batches"] += 1
        st["real_nodes"] += tot_n
        st["pad_nodes"] += n_pad
        st["real_edges"] += tot_e
        st["pad_edges"] += e_pad
        st["real_graphs"] += len(sample_idx)
        st["pad_graphs"] += g_pad
        if pos is not None and pos in self._batch_cache:
            return self._batch_cache[pos]
        if merged is not None:
            # Warm resident set: one vectorized gather from the merged
            # arena, the same shape of work as the in-memory loader.
            arena, offsets = merged
            merged_idx = (
                offsets[sids] + sample_idx - self._shard_starts[sids]
            )
            batch = arena.collate(
                merged_idx,
                head_types=self.head_types or (),
                head_dims=self.head_dims or (),
                num_nodes_pad=n_pad,
                num_edges_pad=e_pad,
                num_graphs_pad=g_pad,
                edge_dim=self.edge_dim,
            )
            return self._maybe_cache(pos, batch)
        first = int(sids[0])
        if bool((sids == first).all()):
            # Single-shard batch: gather straight from the shard's arena —
            # the zero-Python-loop path (dominant for unshuffled epochs and
            # shard-aligned plans).
            shard = resident[first]
            batch = shard.arena.collate(
                sample_idx - shard.base,
                head_types=self.head_types or (),
                head_dims=self.head_dims or (),
                num_nodes_pad=n_pad,
                num_edges_pad=e_pad,
                num_graphs_pad=g_pad,
                edge_dim=self.edge_dim,
            )
        else:
            samples = [
                resident[int(s)].samples[int(i) - resident[int(s)].base]
                for i, s in zip(sample_idx.tolist(), sids.tolist())
            ]
            batch = GraphArena(samples).collate(
                np.arange(len(samples)),
                head_types=self.head_types or (),
                head_dims=self.head_dims or (),
                num_nodes_pad=n_pad,
                num_edges_pad=e_pad,
                num_graphs_pad=g_pad,
                edge_dim=self.edge_dim,
            )
        return self._maybe_cache(pos, batch)

    def _maybe_cache(self, pos, batch):
        if pos is not None:
            # Frozen membership (reshuffle="batch"): cache collations up to
            # the host byte budget, same contract as the in-memory loader.
            import jax as _jax

            nbytes = sum(
                getattr(leaf, "nbytes", 0)
                for leaf in _jax.tree_util.tree_leaves(batch)
            )
            if self._cache_bytes + nbytes <= self._cache_budget:
                self._batch_cache[pos] = batch
                self._cache_bytes += nbytes
        return batch
