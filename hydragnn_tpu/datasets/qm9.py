"""QM9 (GDB-9) loader → list of GraphSample.

Reads the published GDB-9 extended-XYZ format if present under ``<root>/raw/``:

    line 0:  natoms
    line 1:  "gdb <id>  A B C mu alpha homo lumo gap r2 zpve U0 U H G Cv"
    lines 2..natoms+1:  "<element>  x y z  mulliken_charge"
    (then frequencies / SMILES / InChI lines, ignored)

Per-sample targets are the 15 scalar properties in file order; ``PROPERTY_INDEX``
maps the names used by the reference example (free energy G = index 13 here,
index 10 in PyG's reordered target matrix — examples/qm9/qm9.py:18-19).

With no on-disk data, ``load_qm9`` generates a deterministic synthetic
molecular dataset: small random H/C/N/O/F clusters whose "free energy" is a
smooth function of composition and geometry, so example scripts and smoke tests
still exercise the full pipeline offline.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..graphs.sample import GraphSample

ELEMENTS = {"H": 1, "C": 6, "N": 7, "O": 8, "F": 9}

# name → column in the per-file property vector (file order, after the 3
# rotational constants A,B,C).
PROPERTY_NAMES = [
    "A", "B", "C", "mu", "alpha", "homo", "lumo", "gap", "r2", "zpve",
    "U0", "U", "H", "G", "Cv",
]
PROPERTY_INDEX = {name: i for i, name in enumerate(PROPERTY_NAMES)}


def _parse_xyz(path: str) -> Optional[GraphSample]:
    with open(path, "r") as fh:
        lines = fh.readlines()
    natoms = int(lines[0])
    props = np.array(
        [float(t.replace("*^", "e")) for t in lines[1].split()[2:]],
        dtype=np.float64,
    )
    pos = np.empty((natoms, 3), dtype=np.float32)
    z = np.empty((natoms, 1), dtype=np.float32)
    for i, line in enumerate(lines[2 : 2 + natoms]):
        tok = line.replace("*^", "e").split()
        z[i, 0] = ELEMENTS[tok[0]]
        pos[i] = [float(t) for t in tok[1:4]]
    return GraphSample(x=z, pos=pos, y=props.astype(np.float32))


def _synthetic_qm9(num_samples: int, seed: int = 7) -> List[GraphSample]:
    """Deterministic stand-in: clusters of 6-20 atoms; every scalar property is
    a smooth, learnable function of composition and geometry."""
    rng = np.random.default_rng(seed)
    zs = np.array(list(ELEMENTS.values()), dtype=np.float32)
    samples = []
    for _ in range(num_samples):
        n = int(rng.integers(6, 21))
        z = rng.choice(zs, size=(n, 1)).astype(np.float32)
        pos = (rng.random((n, 3)).astype(np.float32) - 0.5) * (2.0 * n ** (1 / 3))
        r2 = float(np.sum(pos**2))
        comp = float(z.sum())
        props = np.zeros(len(PROPERTY_NAMES), dtype=np.float32)
        # Fill every property with a distinct smooth combination so any
        # output_index choice in a config is trainable.
        for k in range(len(PROPERTY_NAMES)):
            props[k] = (
                0.1 * (k + 1) * comp / n
                + 0.01 * r2 / n
                + 0.05 * np.sin(0.1 * (k + 1) * comp)
            )
        samples.append(GraphSample(x=z, pos=pos, y=props))
    return samples


def load_qm9(
    root: str = "dataset/qm9",
    num_samples: Optional[int] = None,
    pre_transform=None,
    pre_filter=None,
) -> List[GraphSample]:
    """QM9 as GraphSamples; raw GDB-9 .xyz files under ``<root>/raw`` if
    available, else the synthetic offline stand-in (1000 samples by default).

    ``pre_transform(sample) -> sample`` and ``pre_filter(sample) -> bool`` mirror
    the PyG hooks the reference example uses (examples/qm9/qm9.py:15-34).
    """
    raw_dir = os.path.join(root, "raw")
    samples: List[GraphSample] = []
    if os.path.isdir(raw_dir):
        files = sorted(f for f in os.listdir(raw_dir) if f.endswith(".xyz"))
        if num_samples is not None:
            files = files[:num_samples]
        for f in files:
            s = _parse_xyz(os.path.join(raw_dir, f))
            if s is not None:
                samples.append(s)
    if not samples:
        print(
            f"load_qm9: no raw GDB-9 files under {raw_dir}; "
            "using the deterministic synthetic offline stand-in."
        )
        samples = _synthetic_qm9(num_samples or 1000)

    if pre_filter is not None:
        samples = [s for s in samples if pre_filter(s)]
    if pre_transform is not None:
        samples = [pre_transform(s) for s in samples]
    return samples
