"""GSHD — the sharded on-disk dataset format of the streaming data plane
(docs/DATA_PLANE.md).

A GSHD dataset is a directory::

    <dataset>/
      gshd_manifest.json      # schema, shard list, per-shard size histograms
      gshd_index.gshd         # per-sample (num_nodes, num_edges) arrays
      shard-00000.gshd        # N samples, v2 digest-verified container
      shard-00001.gshd
      ...

Every ``.gshd`` file is a checkpoint-layer v2 container
(:mod:`..checkpoint.format`): msgpack framing, one sha256 digest per section,
verified BEFORE any deserializer touches the bytes — a flipped byte in a
shard surfaces as :class:`..checkpoint.format.CheckpointCorruptError`, which
the streaming loader routes through its shard quarantine (one shard lost,
loudly, never the run). The manifest is plain JSON written through the same
``atomic_write_json`` the checkpoint sidecars use, and additionally records
each shard file's whole-file sha256 so ``verify`` catches swapped files, not
just flipped bytes.

Sample encoding is exact: each :class:`..graphs.sample.GraphSample` field is
stored with its original dtype and shape (per-sample shape list in the meta
section, concatenated raveled bytes in the field's section), so a decoded
sample is bit-identical to the sample that was written — the foundation of
the streamed-vs-in-memory collation bit-exactness contract
(tests/test_stream.py). Like the checkpoint container, the encoding is
deliberately wall-clock-free: converting the same corpus twice produces
byte-identical shards.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

import msgpack
import numpy as np

from ..checkpoint import format as ckpt_format
from ..checkpoint.io import atomic_write_json, write_checkpoint_blob
from ..graphs.packing import SizeHistogram
from ..graphs.sample import GraphSample

GSHD_MANIFEST_SCHEMA = "hydragnn-gshd-manifest/v1"
GSHD_PRED_SCHEMA = "hydragnn-gshd-predictions/v1"
GSHD_SCHEMA_VERSION = 1
MANIFEST_NAME = "gshd_manifest.json"
INDEX_NAME = "gshd_index.gshd"

#: The one-line migration command named by the pickle-path deprecation
#: warning (preprocess/serialized_loader.py) and the conversion runbook.
CONVERT_CMD = (
    "python -m hydragnn_tpu.datasets convert --config <config.json> <out_dir>"
)

#: GraphSample fields, in a fixed serialization order.
_FIELDS = tuple(f.name for f in dataclasses.fields(GraphSample))


# ------------------------------------------------------------- shard encoding
def encode_shard(samples: List[GraphSample]) -> bytes:
    """Encode one group of samples into a v2 container blob. Each field
    section holds the concatenation of every present sample's raveled
    (C-order) bytes; the meta section records per-sample shapes (``None`` =
    field absent on that sample) and the dtype, so decode reconstructs every
    array exactly."""
    fields_meta: Dict[str, Any] = {}
    sections: Dict[str, Optional[bytes]] = {}
    for name in _FIELDS:
        arrays = [getattr(s, name) for s in samples]
        present = [a for a in arrays if a is not None]
        if not present:
            continue
        dtype = np.asarray(present[0]).dtype
        shapes = []
        chunks = []
        for a in arrays:
            if a is None:
                shapes.append(None)
                continue
            arr = np.asarray(a)
            if arr.dtype != dtype:
                arr = arr.astype(dtype)
            shapes.append(list(arr.shape))
            chunks.append(np.ascontiguousarray(arr).tobytes())
        fields_meta[name] = {"dtype": dtype.str, "shapes": shapes}
        sections[name] = b"".join(chunks)
    meta = {
        "schema_version": GSHD_SCHEMA_VERSION,
        "num_samples": len(samples),
        "fields": fields_meta,
        "ns": [int(s.num_nodes) for s in samples],
        "es": [int(s.num_edges) for s in samples],
    }
    sections["meta"] = msgpack.packb(meta, use_bin_type=True)
    header = {
        "kind": "gshd-shard",
        "schema_version": GSHD_SCHEMA_VERSION,
        "num_samples": len(samples),
    }
    return ckpt_format.encode(sections, header=header)


def decode_shard(blob: bytes, path: str = "<bytes>") -> List[GraphSample]:
    """Digest-verify + decode one shard blob back into GraphSamples. The
    reconstructed arrays are read-only views over the verified buffer (the
    loader's collator copies on gather); corruption raises
    :class:`..checkpoint.format.CheckpointCorruptError` before any field is
    deserialized."""
    header, sections = ckpt_format.decode(blob, path)
    if header.get("kind") != "gshd-shard":
        raise ckpt_format.CheckpointCorruptError(
            path, f"not a gshd shard (kind={header.get('kind')!r})"
        )
    meta = msgpack.unpackb(sections["meta"], raw=False, strict_map_key=False)
    g = int(meta["num_samples"])
    per_sample: List[Dict[str, Optional[np.ndarray]]] = [
        {} for _ in range(g)
    ]
    for name, fmeta in meta["fields"].items():
        dtype = np.dtype(fmeta["dtype"])
        flat = np.frombuffer(sections[name], dtype=dtype)
        off = 0
        for i, shape in enumerate(fmeta["shapes"]):
            if shape is None:
                per_sample[i][name] = None
                continue
            count = int(np.prod(shape)) if shape else 1
            per_sample[i][name] = flat[off : off + count].reshape(shape)
            off += count
        if off != flat.size:
            raise ckpt_format.CheckpointCorruptError(
                path, f"field {name!r}: shape list does not cover the section"
            )
    return [GraphSample(**fields) for fields in per_sample]


def load_shard(path: str) -> List[GraphSample]:
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise ckpt_format.CheckpointCorruptError(
            path, f"unreadable ({e})"
        ) from e
    return decode_shard(blob, path)


def _sha256(blob: bytes) -> str:
    return hashlib.sha256(blob).hexdigest()


# ----------------------------------------------------------------- manifests
def write_gshd(
    out_dir: str,
    samples: Iterable[GraphSample],
    shard_size: int = 256,
    name: str = "dataset",
    minmax_node_feature=None,
    minmax_graph_feature=None,
) -> str:
    """Write a GSHD dataset directory from an iterable of samples (streaming:
    at most ``shard_size`` samples are held in memory). Returns the manifest
    path. Shard installs go through ``write_checkpoint_blob`` (unique tmp +
    fsync + rename) and the manifest through ``atomic_write_json`` — the same
    durability contract as checkpoints."""
    if shard_size < 1:
        raise ValueError(f"shard_size must be >= 1, got {shard_size}")
    os.makedirs(out_dir, exist_ok=True)
    shards = []
    all_ns: List[int] = []
    all_es: List[int] = []
    global_hist = SizeHistogram()
    buf: List[GraphSample] = []

    def flush():
        sid = len(shards)
        fname = f"shard-{sid:05d}.gshd"
        blob = encode_shard(buf)
        write_checkpoint_blob(os.path.join(out_dir, fname), blob)
        hist = SizeHistogram()
        for s in buf:
            n, e = int(s.num_nodes), int(s.num_edges)
            hist.record_graph(n, e)
            global_hist.record_graph(n, e)
            all_ns.append(n)
            all_es.append(e)
        shards.append(
            {
                "file": fname,
                "num_samples": len(buf),
                "bytes": len(blob),
                "sha256": _sha256(blob),
                "size_histogram": hist.to_json(),
            }
        )
        buf.clear()

    first: Optional[GraphSample] = None
    for s in samples:
        if first is None:
            first = s
        buf.append(s)
        if len(buf) >= shard_size:
            flush()
    if buf:
        flush()
    if not shards:
        raise ValueError("cannot write an empty GSHD dataset")

    index_blob = ckpt_format.encode(
        {
            "ns": np.asarray(all_ns, np.int64).tobytes(),
            "es": np.asarray(all_es, np.int64).tobytes(),
        },
        header={"kind": "gshd-index", "num_samples": len(all_ns)},
    )
    write_checkpoint_blob(os.path.join(out_dir, INDEX_NAME), index_blob)

    assert first is not None
    edge_attr_width = 0
    if first.edge_attr is not None and np.ndim(first.edge_attr) == 2:
        edge_attr_width = int(np.shape(first.edge_attr)[1])
    manifest = {
        "schema": GSHD_MANIFEST_SCHEMA,
        "schema_version": GSHD_SCHEMA_VERSION,
        "name": name,
        "num_samples": len(all_ns),
        "shards": shards,
        "index": {
            "file": INDEX_NAME,
            "bytes": len(index_blob),
            "sha256": _sha256(index_blob),
        },
        "fields": {
            "x_width": int(np.shape(first.x)[1]) if first.x is not None else 0,
            "edge_attr_width": edge_attr_width,
            "has_y": bool(first.y is not None),
        },
        "minmax_node_feature": _tolist(minmax_node_feature),
        "minmax_graph_feature": _tolist(minmax_graph_feature),
        "size_histogram": global_hist.to_json(),
    }
    manifest_path = os.path.join(out_dir, MANIFEST_NAME)
    atomic_write_json(manifest_path, manifest)
    return manifest_path


def _tolist(arr):
    if arr is None:
        return None
    return np.asarray(arr).tolist()


def manifest_path_of(path: str) -> str:
    """Resolve a dataset directory OR a manifest file to the manifest path."""
    if os.path.isdir(path):
        return os.path.join(path, MANIFEST_NAME)
    return path


def is_gshd_path(path: str) -> bool:
    """True when ``path`` names a GSHD dataset (its directory, or the
    manifest JSON itself). Cheap: one small-JSON read, no shard access."""
    p = manifest_path_of(path)
    if not (p.endswith(".json") and os.path.isfile(p)):
        return False
    try:
        with open(p) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    return isinstance(doc, dict) and doc.get("schema") == GSHD_MANIFEST_SCHEMA


def read_manifest(path: str) -> Dict[str, Any]:
    p = manifest_path_of(path)
    with open(p) as f:
        doc = json.load(f)
    if doc.get("schema") != GSHD_MANIFEST_SCHEMA:
        raise ValueError(
            f"{p}: not a GSHD manifest "
            f"(schema {doc.get('schema')!r}, expected {GSHD_MANIFEST_SCHEMA!r})"
        )
    doc["_dir"] = os.path.dirname(os.path.abspath(p))
    return doc


def read_index(manifest: Dict[str, Any]) -> Tuple[np.ndarray, np.ndarray]:
    """Digest-verified per-sample (num_nodes, num_edges) arrays — the only
    whole-corpus state the streaming loader keeps in RAM (16 bytes/sample)."""
    path = os.path.join(manifest["_dir"], manifest["index"]["file"])
    with open(path, "rb") as f:
        blob = f.read()
    header, sections = ckpt_format.decode(blob, path)
    if header.get("kind") != "gshd-index":
        raise ckpt_format.CheckpointCorruptError(
            path, f"not a gshd index (kind={header.get('kind')!r})"
        )
    ns = np.frombuffer(sections["ns"], np.int64)
    es = np.frombuffer(sections["es"], np.int64)
    if ns.size != int(manifest["num_samples"]) or es.size != ns.size:
        raise ckpt_format.CheckpointCorruptError(
            path, "index length does not match the manifest sample count"
        )
    return ns, es


def shard_offsets(manifest: Dict[str, Any]) -> np.ndarray:
    """Prefix offsets of each shard's first global sample index (len S+1):
    global sample ``i`` lives in shard ``searchsorted(offsets, i, 'right')-1``
    at local position ``i - offsets[sid]``."""
    sizes = [int(sh["num_samples"]) for sh in manifest["shards"]]
    out = np.zeros(len(sizes) + 1, np.int64)
    np.cumsum(sizes, out=out[1:])
    return out


def iter_samples(path: str, limit: Optional[int] = None) -> Iterator[GraphSample]:
    """Stream every sample in dataset (shard) order — one decoded shard
    resident at a time. The sequential-scan entry point (conversion checks,
    batch inference, visualization)."""
    manifest = read_manifest(path)
    n = 0
    for sh in manifest["shards"]:
        for s in load_shard(os.path.join(manifest["_dir"], sh["file"])):
            yield s
            n += 1
            if limit is not None and n >= limit:
                return


def verify_gshd(path: str) -> Dict[str, Any]:
    """Full integrity check: per-shard whole-file sha256 vs the manifest,
    v2 container digests, per-shard sample counts, and the index. Returns a
    report dict (``ok`` + per-shard verdicts); never raises on corruption."""
    report: Dict[str, Any] = {"ok": True, "shards": [], "errors": []}
    try:
        manifest = read_manifest(path)
    except Exception as e:  # noqa: BLE001 — verify reports, never raises
        return {"ok": False, "shards": [], "errors": [f"manifest: {e}"]}
    total = 0
    for sh in manifest["shards"]:
        entry = {"file": sh["file"], "ok": True, "error": None}
        fpath = os.path.join(manifest["_dir"], sh["file"])
        try:
            with open(fpath, "rb") as f:
                blob = f.read()
            if _sha256(blob) != sh["sha256"]:
                raise ckpt_format.CheckpointCorruptError(
                    fpath, "file sha256 does not match the manifest"
                )
            samples = decode_shard(blob, fpath)
            if len(samples) != int(sh["num_samples"]):
                raise ckpt_format.CheckpointCorruptError(
                    fpath,
                    f"sample count {len(samples)} != manifest "
                    f"{sh['num_samples']}",
                )
            total += len(samples)
        except Exception as e:  # noqa: BLE001 — collected into the report
            entry.update(ok=False, error=str(e))
            report["ok"] = False
            report["errors"].append(f"{sh['file']}: {e}")
        report["shards"].append(entry)
    try:
        read_index(manifest)
    except Exception as e:  # noqa: BLE001 — collected into the report
        report["ok"] = False
        report["errors"].append(f"index: {e}")
    if report["ok"] and total != int(manifest["num_samples"]):
        report["ok"] = False
        report["errors"].append(
            f"total samples {total} != manifest {manifest['num_samples']}"
        )
    report["num_samples"] = total
    report["num_shards"] = len(manifest["shards"])
    return report


# ---------------------------------------------------------------- conversion
def convert_pickle_corpus(
    pkl_path: str,
    out_dir: str,
    config: Optional[Dict[str, Any]] = None,
    shard_size: int = 256,
    name: Optional[str] = None,
) -> str:
    """Migrate one pickle corpus (the 3-pickle minmax/minmax/dataset layout)
    to GSHD. With ``config``, the split is run through
    ``SerializedDataLoader`` first so the shards hold TRAINING-READY samples
    (edges built, targets packed, features selected) and the streaming loader
    does no per-epoch preprocessing; without it the raw samples are stored
    as-is. Returns the manifest path."""
    import pickle

    with open(pkl_path, "rb") as f:
        minmax_node_feature = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(this IS the convert CLI: the one-time migration that reads a legacy pickle corpus to produce digest-verified shards)
        minmax_graph_feature = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(convert CLI migration read, see above)
        dataset = pickle.load(f)  # graftlint: disable=pickle-load-outside-compat(convert CLI migration read, see above)
    if config is not None:
        from ..preprocess.serialized_loader import SerializedDataLoader

        dataset = SerializedDataLoader(config).load_serialized_data(
            dataset_path=pkl_path
        )
    return write_gshd(
        out_dir,
        dataset,
        shard_size=shard_size,
        name=name or os.path.splitext(os.path.basename(pkl_path))[0],
        minmax_node_feature=minmax_node_feature,
        minmax_graph_feature=minmax_graph_feature,
    )
