"""GSHD dataset operations CLI (docs/DATA_PLANE.md "Conversion runbook")::

    python -m hydragnn_tpu.datasets convert --config <config.json> <out_dir>
    python -m hydragnn_tpu.datasets convert <corpus.pkl> <out_dir> [--config c]
    python -m hydragnn_tpu.datasets verify  <dataset_dir | manifest.json> [--json]
    python -m hydragnn_tpu.datasets ls      <dataset_dir | manifest.json> [--json]

``convert`` migrates pickle-era corpora to GSHD. The ``--config``-only form
reads ``Dataset.path`` from the run config (handling the ``total`` layout by
splitting it first, exactly as training would), runs each split through
``SerializedDataLoader`` so shards hold training-ready samples, and prints
the ``Dataset.path`` block to paste back into the config. The two-path form
converts a single pickle corpus (training-ready only when ``--config`` is
given; raw samples otherwise).

``verify`` is the operator preflight for a copied-around dataset directory:
whole-file sha256 vs the manifest, v2 container digests, sample counts, and
the index — nonzero exit on any failure. ``ls`` summarizes the manifest.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import shards


def _convert(args, ap) -> int:
    config = None
    if args.config:
        with open(args.config) as f:
            config = json.load(f)
    if len(args.paths) == 1:
        if config is None:
            ap.error("convert <out_dir> requires --config (or pass "
                     "convert <corpus.pkl> <out_dir>)")
        out_dir = args.paths[0]
        path_map = dict(config["Dataset"]["path"])
        if "total" in path_map:
            from ..preprocess.load_data import total_to_train_val_test_pkls

            total_to_train_val_test_pkls(config)
            path_map = dict(config["Dataset"]["path"])
        new_paths = {}
        for split, pkl in path_map.items():
            split_dir = os.path.join(out_dir, split)
            name = f"{config['Dataset'].get('name', 'dataset')}_{split}"
            manifest = shards.convert_pickle_corpus(
                pkl,
                split_dir,
                config=config,
                shard_size=args.shard_size,
                name=name,
            )
            new_paths[split] = split_dir
            print(f"{split}: {pkl} -> {manifest}")
        print('Update the config\'s "Dataset" -> "path" to:')
        print(json.dumps(new_paths, indent=2))
        return 0
    pkl, out_dir = args.paths
    manifest = shards.convert_pickle_corpus(
        pkl, out_dir, config=config, shard_size=args.shard_size
    )
    print(f"wrote {manifest}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.datasets",
        description="Convert, verify, or list GSHD streaming datasets.",
    )
    ap.add_argument("command", choices=("convert", "verify", "ls"))
    ap.add_argument(
        "paths",
        nargs="+",
        help="convert: [corpus.pkl] out_dir; verify/ls: dataset dir or manifest",
    )
    ap.add_argument("--config", help="run config JSON (training-ready shards)")
    ap.add_argument("--shard-size", type=int, default=256,
                    help="samples per shard (default 256)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    if args.command == "convert":
        if len(args.paths) > 2:
            ap.error("convert takes at most [corpus.pkl] out_dir")
        return _convert(args, ap)

    if len(args.paths) != 1:
        ap.error(f"{args.command} takes exactly one dataset path")
    path = args.paths[0]

    if args.command == "verify":
        report = shards.verify_gshd(path)
        if args.json:
            print(json.dumps(report))
        else:
            for sh in report["shards"]:
                status = "ok" if sh["ok"] else f"CORRUPT: {sh['error']}"
                print(f"{sh['file']}: {status}")
            for err in report["errors"]:
                if not any(err.startswith(s["file"]) for s in report["shards"]):
                    print(f"ERROR: {err}")
            verdict = "ok" if report["ok"] else "FAILED"
            print(
                f"{verdict}: {report['num_samples']} samples in "
                f"{report['num_shards']} shard(s)"
            )
        return 0 if report["ok"] else 1

    manifest = shards.read_manifest(path)
    if args.json:
        doc = {k: v for k, v in manifest.items() if k != "_dir"}
        print(json.dumps(doc))
    else:
        print(
            f"{manifest['name']}: {manifest['num_samples']} samples, "
            f"{len(manifest['shards'])} shard(s), schema "
            f"{manifest['schema']} (fields: {manifest['fields']})"
        )
        for sh in manifest["shards"]:
            print(
                f"  {sh['file']}: {sh['num_samples']} samples, "
                f"{sh['bytes']} bytes"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
