"""MD17 molecular-dynamics trajectory loader → list of GraphSample.

Reads the published sGDML ``.npz`` layout if present (keys ``R`` [frames, n, 3],
``z`` [n], ``E`` [frames, 1], ``F`` [frames, n, 3]) from ``<root>/<name>.npz``
or ``<root>/md17_<name>.npz`` — the same data PyG's ``MD17`` dataset downloads
(reference examples/md17/md17.py:66-71 uses the uracil trajectory).

With no on-disk data, generates a deterministic synthetic trajectory of a fixed
12-atom uracil-like molecule: equilibrium geometry plus smooth sinusoidal
vibrations, energy = harmonic potential of the displacement — learnable, and
shaped exactly like the real thing.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..graphs.sample import GraphSample


def _frames_to_samples(R, z, E, F=None) -> List[GraphSample]:
    samples = []
    z = np.asarray(z, dtype=np.float32).reshape(-1, 1)
    for i in range(R.shape[0]):
        y = np.asarray(E[i], dtype=np.float32).reshape(-1)
        s = GraphSample(
            x=z.copy(), pos=np.asarray(R[i], dtype=np.float32), y=y
        )
        if F is not None:
            s.forces = np.asarray(F[i], dtype=np.float32)  # extra attr, optional
        samples.append(s)
    return samples


def _synthetic_md17(num_frames: int, seed: int = 11) -> List[GraphSample]:
    rng = np.random.default_rng(seed)
    n = 12  # uracil heavy+H atom count (C4H4N2O2)
    z = np.array([6, 6, 6, 6, 7, 7, 8, 8, 1, 1, 1, 1], dtype=np.float32)
    equilibrium = rng.random((n, 3)).astype(np.float32) * 3.0
    modes = rng.normal(size=(3, n, 3)).astype(np.float32) * 0.2
    t = np.linspace(0.0, 20.0 * np.pi, num_frames, dtype=np.float32)
    R = equilibrium[None] + sum(
        np.sin((k + 1) * t)[:, None, None] * modes[k] for k in range(3)
    )
    disp = R - equilibrium[None]
    E = 0.5 * (disp**2).sum(axis=(1, 2), keepdims=False).reshape(-1, 1)
    return _frames_to_samples(R, np.tile(z, 1), E)


def load_md17(
    root: str = "dataset/md17",
    name: str = "uracil",
    num_samples: Optional[int] = None,
    pre_transform=None,
    pre_filter=None,
) -> List[GraphSample]:
    """MD17 trajectory as GraphSamples; sGDML npz under ``root`` if available,
    else the synthetic offline stand-in (1000 frames by default)."""
    samples: List[GraphSample] = []
    for candidate in (f"{name}.npz", f"md17_{name}.npz", f"rmd17_{name}.npz"):
        path = os.path.join(root, candidate)
        if os.path.exists(path):
            data = np.load(path)
            # sGDML files use R/z/E/F; revised-MD17 (rMD17) archives use
            # coords/nuclear_charges/energies/forces.
            R = data["R"] if "R" in data else data["coords"]
            z = data["z"] if "z" in data else data["nuclear_charges"]
            E = data["E"] if "E" in data else data["energies"]
            if E.ndim == 1:
                E = E.reshape(-1, 1)
            F = data["F"] if "F" in data else data.get("forces")
            if num_samples is not None:
                R, E = R[:num_samples], E[:num_samples]
                F = F[:num_samples] if F is not None else None
            samples = _frames_to_samples(R, z, E, F)
            break
    if not samples:
        print(
            f"load_md17: no {name} npz under {root}; "
            "using the deterministic synthetic offline stand-in."
        )
        samples = _synthetic_md17(num_samples or 1000)

    if pre_filter is not None:
        samples = [s for s in samples if pre_filter(s)]
    if pre_transform is not None:
        samples = [pre_transform(s) for s in samples]
    return samples
