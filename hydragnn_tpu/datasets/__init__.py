"""Self-contained dataset loaders (no torch_geometric dependency).

The reference examples lean on PyG's built-in ``QM9``/``MD17`` download-and-cache
datasets (/root/reference/examples/qm9/qm9.py:63-65, examples/md17/md17.py:66-71).
Here the loaders read the standard on-disk formats directly and, when no data is
present (e.g. air-gapped CI), fall back to a clearly-announced deterministic
synthetic stand-in so every example stays runnable offline.
"""

from .md17 import load_md17
from .qm9 import load_qm9

__all__ = ["load_qm9", "load_md17"]
