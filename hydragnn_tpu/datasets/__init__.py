"""Self-contained dataset loaders (no torch_geometric dependency).

The reference examples lean on PyG's built-in ``QM9``/``MD17`` download-and-cache
datasets (/root/reference/examples/qm9/qm9.py:63-65, examples/md17/md17.py:66-71).
Here the loaders read the standard on-disk formats directly and, when no data is
present (e.g. air-gapped CI), fall back to a clearly-announced deterministic
synthetic stand-in so every example stays runnable offline.
"""

from .md17 import load_md17
from .qm9 import load_qm9
from .shards import (
    CONVERT_CMD,
    convert_pickle_corpus,
    is_gshd_path,
    iter_samples,
    read_manifest,
    verify_gshd,
    write_gshd,
)
from .stream import ShardRing, StreamingGraphLoader, plan_shard_ring

__all__ = [
    "load_qm9",
    "load_md17",
    "CONVERT_CMD",
    "convert_pickle_corpus",
    "is_gshd_path",
    "iter_samples",
    "read_manifest",
    "verify_gshd",
    "write_gshd",
    "ShardRing",
    "StreamingGraphLoader",
    "plan_shard_ring",
]
