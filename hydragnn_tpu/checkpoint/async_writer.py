"""Non-blocking checkpointing (docs/CHECKPOINTING.md "Async lifecycle").

The synchronous ``save_model`` holds the training thread through serialize +
fsync + rename — tens to hundreds of milliseconds the accelerator sits idle
every checkpoint epoch. :class:`AsyncCheckpointer` splits the save at the
only point that NEEDS the training thread: the device→host snapshot.

Lifecycle per ``save()`` call (training thread):

1. ``wait()`` — barrier on the PREVIOUS save (bounded in-flight of one write;
   also where a prior writer failure re-raises, so errors are never swallowed
   more than one save interval).
2. Device→host snapshot of params/batch_stats/opt_state (``np.asarray`` per
   leaf) + a deep copy of ``meta`` (the caller keeps mutating its history
   dict between epochs).
3. Enqueue for the single daemon writer thread, which runs the SAME
   ``io.save_model`` implementation as a sync save — serialize, fsync,
   atomic rename, retention, post-save fault hook. Sync and async payloads
   are byte-identical by construction (one serializer).

``wait()`` at run exit (or ``close()``) drains the queue and re-raises any
writer failure; a checkpoint that failed to persist must fail the run, not
vanish into a dead thread.
"""

from __future__ import annotations

import copy
import queue
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ..analysis import tsan
from . import io as ckpt_io


class AsyncCheckpointer:
    """Single-writer asynchronous checkpoint front end. One instance per run;
    the writer thread is lazily started and torn down by ``close()``."""

    def __init__(self, max_inflight: int = 1):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, int(max_inflight)))
        self._lock = tsan.instrument_lock(
            threading.Lock(), "AsyncCheckpointer._lock"
        )
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None  # guarded-by: self._lock
        self._closed = False

    # ------------------------------------------------------------- internals
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name="ckpt-writer", daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            try:
                tsan.yield_point("ckpt.worker.pre_save")
                ckpt_io.save_model(**job)
            except BaseException as e:  # re-raised on the training thread
                with self._lock:
                    self._error = e
                    tsan.shared_access("AsyncCheckpointer.error")
            finally:
                self._queue.task_done()

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
            tsan.shared_access("AsyncCheckpointer.error")
        if err is not None:
            raise RuntimeError(
                "async checkpoint writer failed; the last checkpoint was NOT "
                "persisted"
            ) from err

    # ----------------------------------------------------------------- api
    def save(
        self,
        variables: Dict[str, Any],
        opt_state: Any,
        name: str,
        path: str = "./logs/",
        meta: Optional[Dict[str, Any]] = None,
        keep_last_k: int = 0,
    ) -> float:
        """Snapshot + enqueue; returns the training-thread stall in seconds
        (the whole point of the async path — compare against a sync save's
        wall time, ``ckpt_save_stall_ms`` in the FAULTS artifact)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        if not ckpt_io._is_rank_zero():
            return 0.0
        t0 = time.perf_counter()
        # Barrier at the next save: previous write complete (or its failure
        # raised HERE, at the first wait point after it happened).
        self.wait()
        host_vars = {
            "params": _to_host(variables["params"]),
            "batch_stats": _to_host(variables.get("batch_stats", {})),
        }
        host_opt = _to_host(opt_state) if opt_state is not None else None
        job = {
            "variables": host_vars,
            "opt_state": host_opt,
            "name": name,
            "path": path,
            "meta": copy.deepcopy(meta),
            "keep_last_k": keep_last_k,
        }
        self._ensure_thread()
        tsan.yield_point("ckpt.save.pre_enqueue")
        self._queue.put(job)
        from ..faults import FaultCounters

        FaultCounters.inc("ckpt_async_saves")
        return time.perf_counter() - t0

    def wait(self) -> None:
        """Drain every queued write, then re-raise any writer failure."""
        self._queue.join()
        self._raise_pending()

    def close(self, raise_errors: bool = True) -> None:
        """Flush and stop the writer. ``raise_errors=False`` is for exception
        paths where a writer failure must not mask the original error."""
        if self._closed:
            return
        try:
            if raise_errors:
                self.wait()
            else:
                self._queue.join()
        finally:
            self._closed = True
            if self._thread is not None and self._thread.is_alive():
                self._queue.put(None)
                self._thread.join(timeout=10.0)


def _to_host(tree):
    """Device→host snapshot: every array leaf becomes host numpy NOW, so the
    donating train step can reuse the device buffers the moment save()
    returns. Non-array leaves (step counts, None) pass through."""
    import jax

    return jax.tree_util.tree_map(
        lambda leaf: np.asarray(leaf) if hasattr(leaf, "shape") else leaf, tree
    )
