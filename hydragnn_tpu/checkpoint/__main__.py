"""Checkpoint operations CLI (docs/CHECKPOINTING.md "Migration")::

    python -m hydragnn_tpu.checkpoint verify  <run_dir | file.pk> [--json]
    python -m hydragnn_tpu.checkpoint migrate <run_dir | file.pk> [--json]

``verify`` integrity-checks every checkpoint (v2 digest verification, v1
structural decode) and exits nonzero if any file fails — the preflight an
operator runs before trusting a copied-around run directory. ``migrate``
rewrites v1 pickle checkpoints as v2 in place (atomic); corrupt files are
reported and left untouched.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List

from .io import migrate_run_dir, verify_checkpoint_file


def _targets(path: str) -> List[str]:
    if os.path.isdir(path):
        return sorted(glob.glob(os.path.join(path, "*.pk")))
    return [path]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.checkpoint",
        description="Verify or migrate hydragnn_tpu checkpoints.",
    )
    ap.add_argument("command", choices=("verify", "migrate"))
    ap.add_argument("path", help="run directory (logs/<name>) or one .pk file")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)

    if args.command == "verify":
        reports = [verify_checkpoint_file(p) for p in _targets(args.path)]
        bad = [r for r in reports if not r["ok"]]
        if args.json:
            print(json.dumps({"reports": reports, "ok": not bad}))
        else:
            for r in reports:
                status = (
                    f"ok (v{r['format_version']}, epoch {r.get('epoch')})"
                    if r["ok"]
                    else f"CORRUPT: {r['error']}"
                )
                print(f"{r['file']}: {status}")
        return 1 if bad or not reports else 0

    result: dict
    if os.path.isdir(args.path):
        result = migrate_run_dir(args.path)
    else:
        from .io import migrate_checkpoint

        try:
            migrated = migrate_checkpoint(args.path)
            result = {
                "migrated": [args.path] if migrated else [],
                "already_v2": [] if migrated else [args.path],
                "failed": [],
            }
        except Exception as e:
            result = {"migrated": [], "already_v2": [], "failed": [args.path],
                      "error": str(e)}
    if args.json:
        print(json.dumps(result))
    else:
        for key in ("migrated", "already_v2", "failed"):
            for p in result[key]:
                print(f"{key}: {p}")
    return 1 if result["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
