"""Verified, asynchronous, self-healing checkpointing (docs/CHECKPOINTING.md).

Three pillars:

* :mod:`.format` — the v2 integrity-checked container: msgpack (never pickle
  on load), a header with format version / epoch / param-tree fingerprint,
  and per-section sha256 digests verified on every load. v1 pickle files
  remain readable through a deprecation window.
* :mod:`.io` — atomic writes with writer-owned unique tmp names, keep_last_k
  retention, and the corruption fallback chain: a torn/bit-flipped latest
  checkpoint falls back to the newest intact retained entry, recorded in
  ``FaultCounters`` and ``supervisor.json``, failing only when the whole
  chain is exhausted.
* :mod:`.async_writer` — non-blocking saves: device→host snapshot on the
  training thread, serialize/fsync/rename on a single background writer,
  ``wait()`` barriers at the next save and run exit, writer failures
  re-raised rather than swallowed.

``utils/model.py`` keeps the historical public names (``save_model``,
``load_existing_model``, ...) as thin wrappers over this package.

CLI: ``python -m hydragnn_tpu.checkpoint {verify,migrate} <run_dir>``.
"""

from .async_writer import AsyncCheckpointer
from .format import (
    FORMAT_VERSION,
    MAGIC,
    MIGRATE_CMD,
    CheckpointChainExhaustedError,
    CheckpointCorruptError,
    CheckpointError,
    content_identity,
    file_content_identity,
    param_fingerprint,
)
from .io import (
    atomic_write_json,
    checkpoint_exists,
    cleanup_stale_checkpoint_tmp,
    load_checkpoint_bytes,
    load_checkpoint_file,
    load_checkpoint_manifest,
    load_checkpoint_meta,
    load_existing_model,
    load_existing_model_config,
    load_verified_chain,
    migrate_checkpoint,
    migrate_run_dir,
    record_checkpoint_fallback,
    save_model,
    serialize_checkpoint,
    set_post_save_hook,
    update_checkpoint_meta,
    verify_checkpoint_file,
)

__all__ = [
    "FORMAT_VERSION",
    "MAGIC",
    "MIGRATE_CMD",
    "AsyncCheckpointer",
    "atomic_write_json",
    "CheckpointChainExhaustedError",
    "CheckpointCorruptError",
    "CheckpointError",
    "checkpoint_exists",
    "cleanup_stale_checkpoint_tmp",
    "content_identity",
    "file_content_identity",
    "load_checkpoint_bytes",
    "load_checkpoint_file",
    "load_checkpoint_manifest",
    "load_checkpoint_meta",
    "load_existing_model",
    "load_existing_model_config",
    "load_verified_chain",
    "migrate_checkpoint",
    "migrate_run_dir",
    "param_fingerprint",
    "record_checkpoint_fallback",
    "save_model",
    "serialize_checkpoint",
    "set_post_save_hook",
    "update_checkpoint_meta",
    "verify_checkpoint_file",
]
