"""Checkpoint read/write, retention, and the corruption fallback chain
(docs/CHECKPOINTING.md).

This module absorbed the checkpoint half of ``utils/model.py`` (which keeps
the public names as thin wrappers). Three contracts live here:

* **Write**: one serializer (:func:`serialize_checkpoint`, v2 container) feeds
  both the synchronous :func:`save_model` and the async writer — sync and
  async saves of the same state are byte-identical. Writes are tmp + fsync +
  ``os.replace`` with WRITER-OWNED unique tmp names (pid + sequence), so a
  concurrent async writer and a foreign process can never collide on a tmp
  path, and save entry never deletes anyone else's tmp (stale-tmp cleanup is
  scoped to run startup, where no write can be in flight).

* **Verified read**: :func:`load_checkpoint_file` sniffs v2 (magic) vs v1
  (legacy pickle). v2 loads verify every section digest before any
  deserializer runs; v1 read-compat survives but emits a one-time
  ``DeprecationWarning`` naming the migration command.

* **Fallback chain**: :func:`load_verified_chain` tries the latest file, then
  walks the ``keep_last_k`` manifest newest→oldest, loading the first intact
  entry. Every corrupt candidate increments ``FaultCounters`` and the
  successful fallback is recorded in the run's ``supervisor.json``
  (``checkpoint_fallbacks``: which file, why, how many epochs lost). Only an
  exhausted chain raises.
"""

from __future__ import annotations

import glob
import itertools
import json
import os
import pickle
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from flax import serialization

from . import format as ckpt_format
from .format import (
    MIGRATE_CMD,
    CheckpointChainExhaustedError,
    CheckpointCorruptError,
    CheckpointError,
)


def _is_rank_zero() -> bool:
    import jax

    return jax.process_index() == 0


# Writer-owned unique tmp names: <final>.<pid>.<seq>.tmp — two writers (the
# async thread plus a stray sync save, or two processes on shared storage)
# can never collide, and cleanup never has to guess whether a tmp is live.
_tmp_seq = itertools.count()


def _unique_tmp(path_name: str) -> str:
    return f"{path_name}.{os.getpid()}.{next(_tmp_seq)}.tmp"


def atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    """THE atomic JSON install (unique tmp + fsync + rename) for every
    checkpoint-adjacent sidecar — retention manifest, supervisor.json. One
    implementation so the sidecars carry the same durability contract as the
    checkpoints they describe."""
    tmp = _unique_tmp(path)
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def atomic_copy_file(src: str, dst: str) -> None:
    """Atomic byte-copy install (unique tmp + fsync + rename): the forensic
    sibling of :func:`atomic_write_json` for copying an existing artifact
    (e.g. a rejected candidate into quarantine). A crash mid-copy leaves
    only a writer-owned ``.tmp``, never a torn half-copy at ``dst`` that a
    later reader would mistake for the real bytes."""
    import shutil

    tmp = _unique_tmp(dst)
    with open(src, "rb") as fsrc, open(tmp, "wb") as f:
        shutil.copyfileobj(fsrc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, dst)


def cleanup_stale_checkpoint_tmp(run_dir: str) -> List[str]:
    """Remove ``*.tmp`` files a crash left behind mid-``os.replace``. Scoped
    to RUN STARTUP only (run_training bootstrap, supervisor entry) — at
    startup no writer exists yet, so any ``.tmp`` present is by construction
    a torn leftover. Never called at save entry: with the async writer a
    ``.tmp`` there may be a LIVE in-flight write. Returns the removed paths
    (logged by the fault drills)."""
    removed = []
    for p in glob.glob(os.path.join(run_dir, "*.tmp")):
        try:
            os.remove(p)
            removed.append(p)
        except OSError:
            pass
    return removed


# --------------------------------------------------------------------------
# post-save fault hook (drills: corrupt_ckpt / truncate_ckpt / kill@save)
# --------------------------------------------------------------------------

_post_save_hook: Optional[Callable[[str], None]] = None


def set_post_save_hook(hook: Optional[Callable[[str], None]]) -> None:
    """Install (or clear, with ``None``) the callable invoked with the final
    checkpoint path after every completed save — sync or async. The fault
    plan's checkpoint drills (``corrupt_ckpt@K``/``truncate_ckpt@K``/
    ``kill@saveK``) register here via the TrainingDriver."""
    global _post_save_hook
    _post_save_hook = hook


# --------------------------------------------------------------- manifests


def _manifest_path(run_dir: str, name: str) -> str:
    return os.path.join(run_dir, name + ".manifest.json")


def load_checkpoint_manifest(name: str, path: str = "./logs/") -> Dict[str, Any]:
    """The retention manifest written by ``save_model(keep_last_k=...)``
    ({} when retention was never enabled, or the manifest is torn)."""
    try:
        with open(_manifest_path(os.path.join(path, name), name)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def role_pinned_files(run_dir: str, name: str) -> set:
    """Checkpoint files pinned against retention GC by a ModelRegistry role.

    The lifecycle sidecar (``<name>.lifecycle.json``, written atomically by
    lifecycle/registry.py) names the files holding the live/candidate/
    previous roles. Those are promotion/rollback targets: with a flywheel
    staging a candidate per save, a plain last-k walk would eventually
    delete the rollback target out from under ``rollback()``. Reading the
    sidecar here (instead of an in-process pin registry) keeps the pin
    honest across processes — the trainer prunes, the supervisor promotes,
    and they only share the run directory. A torn/absent sidecar pins
    nothing (roles were never assigned, or lifecycle is not in play)."""
    try:
        with open(os.path.join(run_dir, name + ".lifecycle.json")) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return set()
    pinned = set()
    for rec in (doc.get("roles") or {}).values():
        if isinstance(rec, dict) and rec.get("file"):
            pinned.add(os.path.basename(str(rec["file"])))
    return pinned


def _retain_checkpoints(
    run_dir: str, name: str, latest: str, keep_last_k: int, meta
) -> None:
    """keep_last_k retention: hard-link the just-written latest checkpoint to
    an epoch-tagged retained file, prune retained files beyond k, and update
    the manifest ATOMICALLY (unique tmp + os.replace) — a crash at any point
    leaves either the old or the new manifest, both listing only files that
    exist."""
    epoch = (meta or {}).get("epoch")
    try:
        with open(_manifest_path(run_dir, name)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = {}
    entries = [
        e
        for e in manifest.get("entries", [])
        if os.path.exists(os.path.join(run_dir, e["file"]))
    ]
    serial = (max((e.get("serial", 0) for e in entries), default=0)) + 1
    tag = f"e{int(epoch):06d}" if epoch is not None else f"s{serial:06d}"
    retained = f"{name}.{tag}.pk"
    retained_path = os.path.join(run_dir, retained)
    link_tmp = _unique_tmp(retained_path)
    try:
        os.link(latest, link_tmp)  # same content, no second serialization
        os.replace(link_tmp, retained_path)
    except OSError:
        import shutil  # filesystems without hard links

        shutil.copyfile(latest, link_tmp)
        os.replace(link_tmp, retained_path)
    entries = [e for e in entries if e["file"] != retained]
    entries.append(
        {
            "file": retained,
            "epoch": epoch,
            "serial": serial,
            "saved_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
    )
    entries.sort(key=lambda e: e["serial"])
    # Role-pinned files (live/candidate/previous per the lifecycle sidecar)
    # are exempt from the last-k walk: they stay on disk AND in the manifest
    # (the fallback chain and registry.versions() walk manifest entries), so
    # rollback targets survive any number of subsequent saves.
    if keep_last_k > 0:
        pinned = role_pinned_files(run_dir, name)
        kept = entries[-keep_last_k:]
        for drop in entries[:-keep_last_k]:
            if drop["file"] in pinned:
                kept.append(drop)
                continue
            try:
                os.remove(os.path.join(run_dir, drop["file"]))
            except OSError:
                pass
        kept.sort(key=lambda e: e["serial"])
        entries = kept
    doc = {"name": name, "keep_last_k": keep_last_k, "entries": entries}
    atomic_write_json(_manifest_path(run_dir, name), doc)


# ------------------------------------------------------------------- write


def _canonical(tree):
    """Identity tree_map: rebuilds every dict level in jax's canonical
    (sorted) key order. flax serializes dicts in ITERATION order, so without
    this a tree that went through a pytree transform (the async writer's
    host snapshot) would serialize different bytes than the original
    insertion-ordered dict — breaking the sync/async byte-identity
    contract."""
    import jax

    return jax.tree_util.tree_map(lambda x: x, tree)


def serialize_checkpoint(
    variables: Dict[str, Any],
    opt_state: Any = None,
    meta: Optional[Dict[str, Any]] = None,
) -> bytes:
    """THE checkpoint serializer: state → v2 container bytes. Shared by the
    sync save path and the async writer thread, so the two cannot diverge —
    the async/sync byte-identity test pins exactly this property."""
    sections = {
        "params": serialization.to_bytes(_canonical(variables["params"])),
        "batch_stats": serialization.to_bytes(
            _canonical(variables.get("batch_stats", {}))
        ),
        "opt_state": serialization.to_bytes(_canonical(opt_state))
        if opt_state is not None
        else None,
        "meta": ckpt_format.pack_meta(meta),
    }
    header = {
        "epoch": (meta or {}).get("epoch"),
        "step": (meta or {}).get("step"),
        "param_fingerprint": ckpt_format.param_fingerprint(variables["params"]),
    }
    return ckpt_format.encode(sections, header)


def write_checkpoint_blob(path_name: str, blob: bytes) -> None:
    """Durable atomic install: unique tmp → write → flush+fsync → rename. The
    fsync is what makes the integrity story real — without it a crash after
    os.replace can still leave a torn file on power loss."""
    tmp_name = _unique_tmp(path_name)
    with open(tmp_name, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp_name, path_name)


def save_model(
    variables: Dict[str, Any],
    opt_state: Any,
    name: str,
    path: str = "./logs/",
    meta: Optional[Dict[str, Any]] = None,
    keep_last_k: int = 0,
) -> None:
    """Rank-0 single-file checkpoint in the v2 verified format. ``meta``
    carries training progress (epoch, scheduler state, loss history) so a
    preempted run can resume exactly where it stopped (Training.resume).

    ``keep_last_k > 0`` additionally retains the last k checkpoints as
    epoch-tagged hard links next to the latest (``<name>.e000004.pk``) with an
    atomically-updated ``<name>.manifest.json`` — the corruption fallback
    chain walks exactly those entries. The ``<name>.pk`` latest-checkpoint
    contract is unchanged either way."""
    if not _is_rank_zero():
        return
    path_name = os.path.join(path, name, name + ".pk")
    run_dir = os.path.dirname(path_name)
    os.makedirs(run_dir, exist_ok=True)
    blob = serialize_checkpoint(variables, opt_state, meta)
    write_checkpoint_blob(path_name, blob)
    if keep_last_k and keep_last_k > 0:
        _retain_checkpoints(run_dir, name, path_name, int(keep_last_k), meta)
    hook = _post_save_hook
    if hook is not None:
        hook(path_name)


# -------------------------------------------------------------------- read

_v1_warned = False


def _warn_v1_once(path_name: str) -> None:
    global _v1_warned
    if _v1_warned:
        return
    _v1_warned = True
    warnings.warn(
        f"{path_name} is a legacy v1 pickle checkpoint (no integrity digests, "
        f"pickle.load on arbitrary bytes). Migrate it with `{MIGRATE_CMD}`; "
        "v1 read-compat will be removed after the migration window.",
        DeprecationWarning,
        stacklevel=3,
    )


def read_checkpoint_payload(path_name: str) -> Tuple[int, Dict[str, Any]]:
    """Raw payload of one checkpoint file → (format_version, payload);
    see :func:`payload_from_blob`."""
    try:
        with open(path_name, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CheckpointCorruptError(path_name, f"unreadable ({e})") from e
    return payload_from_blob(blob, path_name)


def payload_from_blob(blob: bytes, path_name: str = "<bytes>") -> Tuple[int, Dict[str, Any]]:
    """Raw payload of one checkpoint BLOB → (format_version, payload) where
    payload is the v1-shaped dict {params: bytes, batch_stats: bytes,
    opt_state: bytes|None, meta: dict, header: dict}. Integrity-verifies v2
    digests; wraps every v1 pickle failure as CheckpointCorruptError so the
    fallback chain can classify it. Split from the file reader so callers
    that already hold the bytes (the lifecycle registry's one-read
    identity+load path) never re-read — identity and deserialization then
    provably attest the SAME bytes."""
    if ckpt_format.is_v2_blob(blob):
        header, sections = ckpt_format.decode(blob, path_name)
        meta = (
            ckpt_format.unpack_meta(sections["meta"]) if "meta" in sections else {}
        )
        payload = {
            "params": sections.get("params"),
            "batch_stats": sections.get("batch_stats"),
            "opt_state": sections.get("opt_state"),
            "meta": meta,
            "header": header,
        }
        if payload["params"] is None:
            raise CheckpointCorruptError(path_name, "missing params section")
        return ckpt_format.FORMAT_VERSION, payload
    # v1 legacy pickle. Any decode failure — truncation, a flipped byte in
    # the pickle stream, a non-dict payload — is corruption.
    try:
        # graftlint: disable=pickle-load-outside-compat(THE sanctioned v1-compat shim: the one place legacy headerless checkpoints may be unpickled, behind _warn_v1_once)
        payload = pickle.loads(blob)
    except Exception as e:
        raise CheckpointCorruptError(
            path_name, f"v1 pickle undecodable ({type(e).__name__}: {e})"
        ) from e
    if not isinstance(payload, dict) or "params" not in payload:
        raise CheckpointCorruptError(path_name, "v1 payload is not a checkpoint dict")
    _warn_v1_once(path_name)
    payload.setdefault("meta", {})
    payload["meta"] = payload.get("meta") or {}
    payload["header"] = {"format_version": 1}
    return 1, payload


def load_checkpoint_file(
    variables: Dict[str, Any], path_name: str, opt_state: Any = None
):
    """Restore one checkpoint FILE onto a variables template. The single
    deserialization implementation — the log-name convenience wrappers and
    direct-path consumers (serve engine) share it, so a payload-schema change
    cannot diverge them. Verifies v2 digests (and the param-tree fingerprint)
    before deserializing; raises CheckpointCorruptError on integrity
    failures. Returns (variables, opt_state, meta)."""
    version, payload = read_checkpoint_payload(path_name)
    return _deserialize_payload(variables, version, payload, path_name, opt_state)


def load_checkpoint_bytes(
    variables: Dict[str, Any],
    blob: bytes,
    path_name: str = "<bytes>",
    opt_state: Any = None,
):
    """:func:`load_checkpoint_file` over in-memory bytes — one read shared
    between identity computation and deserialization (the lifecycle
    registry's TOCTOU-free candidate load: a trainer overwriting the file
    between the two cannot desync what was verified from what was loaded)."""
    version, payload = payload_from_blob(blob, path_name)
    return _deserialize_payload(variables, version, payload, path_name, opt_state)


def _deserialize_payload(
    variables: Dict[str, Any],
    version: int,
    payload: Dict[str, Any],
    path_name: str,
    opt_state: Any = None,
):
    fp = payload["header"].get("param_fingerprint")
    if version >= 2 and fp:
        want = ckpt_format.param_fingerprint(variables["params"])
        if fp != want:
            # Deliberately NOT CheckpointCorruptError: a wrong-model load is
            # an operator error the fallback chain must not paper over.
            raise CheckpointError(
                f"{path_name}: param-tree fingerprint mismatch — this "
                "checkpoint was saved from a different model/config than "
                "the load template"
            )
    try:
        new_vars = dict(variables)
        new_vars["params"] = serialization.from_bytes(
            variables["params"], payload["params"]
        )
        new_vars["batch_stats"] = serialization.from_bytes(
            variables.get("batch_stats", {}), payload["batch_stats"]
        )
        if opt_state is not None and payload.get("opt_state") is not None:
            opt_state = serialization.from_bytes(opt_state, payload["opt_state"])
    except CheckpointError:
        raise
    except Exception as e:
        # Digest-verified v2 sections should never land here; v1 sections
        # have no digests, so undecodable msgpack inside them IS corruption.
        raise CheckpointCorruptError(
            path_name, f"section deserialization failed ({type(e).__name__}: {e})"
        ) from e
    return new_vars, opt_state, payload.get("meta") or {}


def verify_checkpoint_file(path_name: str) -> Dict[str, Any]:
    """Non-raising integrity report for one file (the ``verify`` CLI):
    {file, ok, format_version?, epoch?, error?}."""
    report: Dict[str, Any] = {"file": path_name}
    try:
        version, payload = read_checkpoint_payload(path_name)
    except CheckpointError as e:
        report.update(ok=False, error=str(e))
        return report
    report.update(
        ok=True,
        format_version=version,
        epoch=(payload.get("meta") or {}).get("epoch"),
    )
    return report


# -------------------------------------------------- corruption fallback chain


def record_checkpoint_fallback(run_dir: str, event: Dict[str, Any]) -> None:
    """Append a fallback event to the run's ``supervisor.json``
    (``checkpoint_fallbacks`` list), creating the file if the run was never
    supervised — restart tooling and the drill matrix read it either way.
    Atomic read-modify-write; rank-0 callers only."""
    path = os.path.join(run_dir, "supervisor.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    doc.setdefault("checkpoint_fallbacks", []).append(event)
    atomic_write_json(path, doc)


def load_verified_chain(
    variables: Dict[str, Any],
    run_dir: str,
    name: str,
    opt_state: Any = None,
):
    """The self-healing load: try ``<name>.pk``, then walk the ``keep_last_k``
    manifest newest→oldest, returning the first intact checkpoint. Returns
    (variables, opt_state, meta, report) where report is None for a clean
    latest-file load and otherwise {fallback_file, failures, epochs_lost}.

    Every corrupt candidate increments ``FaultCounters['ckpt_corrupt_detected']``;
    a successful fallback increments ``ckpt_fallback_loads`` and is recorded
    in the run's supervisor.json (rank 0). Raises
    :class:`CheckpointChainExhaustedError` only when no candidate survives."""
    from ..faults import FaultCounters

    latest = os.path.join(run_dir, name + ".pk")
    try:
        with open(_manifest_path(run_dir, name)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        manifest = {}
    entries = sorted(
        manifest.get("entries", []), key=lambda e: e.get("serial", 0), reverse=True
    )
    candidates: List[Tuple[str, Optional[Dict[str, Any]]]] = [(latest, None)]
    for e in entries:
        # The newest retained entry often hard-links the latest file — same
        # inode, same (possibly corrupt) bytes. It is tried anyway: the try
        # is cheap, the failure is counted honestly, and the chain keeps
        # walking to the first genuinely intact entry.
        candidates.append((os.path.join(run_dir, e["file"]), e))
    failures: List[Dict[str, str]] = []
    for path_name, entry in candidates:
        if not os.path.exists(path_name):
            failures.append({"file": path_name, "reason": "missing"})
            continue
        try:
            new_vars, new_opt, meta = load_checkpoint_file(
                variables, path_name, opt_state
            )
        except CheckpointCorruptError as e:
            FaultCounters.inc("ckpt_corrupt_detected")
            failures.append({"file": path_name, "reason": e.reason})
            continue
        if not failures:
            return new_vars, new_opt, meta, None
        # Fallback engaged: quantify the loss (epochs between the manifest's
        # newest entry and what we actually recovered).
        newest_epoch = next(
            (e.get("epoch") for e in entries if e.get("epoch") is not None), None
        )
        got_epoch = meta.get("epoch")
        epochs_lost = (
            int(newest_epoch) - int(got_epoch)
            if newest_epoch is not None and got_epoch is not None
            else None
        )
        report = {
            "fallback_file": os.path.basename(path_name),
            "failures": failures,
            "epoch": got_epoch,
            "epochs_lost": epochs_lost,
        }
        FaultCounters.inc("ckpt_fallback_loads")
        # Flight-recorder trigger (docs/OBSERVABILITY.md): the timeline that
        # led into a fallback load — what was happening when the latest
        # checkpoint turned out corrupt — next to the supervisor.json record.
        from ..telemetry import graftel as telemetry

        telemetry.flight_dump(
            "checkpoint_fallback", run_dir=run_dir, extra=report
        )
        if _is_rank_zero():
            try:
                record_checkpoint_fallback(
                    run_dir,
                    {
                        "ts_utc": time.strftime(
                            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                        ),
                        "loaded_file": report["fallback_file"],
                        "rejected": failures,
                        "epoch": got_epoch,
                        "epochs_lost": epochs_lost,
                    },
                )
            except OSError:
                # A read-only run dir (serving from an artifact mount) must
                # not turn a SUCCESSFUL recovery into a failure; the counters
                # and the log line below still carry the event.
                pass
            from ..utils.print_utils import log

            log(
                f"checkpoint fallback: {len(failures)} corrupt/missing "
                f"candidate(s) skipped, restored {report['fallback_file']} "
                f"(epoch {got_epoch}, {epochs_lost} epoch(s) lost)"
            )
        return new_vars, new_opt, meta, report
    raise CheckpointChainExhaustedError(run_dir, failures)


def load_existing_model(
    variables: Dict[str, Any],
    model_name: str,
    path: str = "./logs/",
    opt_state: Any = None,
    return_meta: bool = False,
    fallback: bool = True,
):
    """Restore params/batch_stats (+optimizer state if given a template) from
    the run's checkpoint, through the verified fallback chain by default
    (``fallback=False`` loads exactly ``<name>.pk`` or raises). Returns
    (variables, opt_state), plus the progress meta dict when ``return_meta``
    (one file read, not two)."""
    run_dir = os.path.join(path, model_name)
    if fallback:
        new_vars, opt_state, meta, _report = load_verified_chain(
            variables, run_dir, model_name, opt_state
        )
    else:
        new_vars, opt_state, meta = load_checkpoint_file(
            variables, os.path.join(run_dir, model_name + ".pk"), opt_state
        )
    if return_meta:
        return new_vars, opt_state, meta
    return new_vars, opt_state


def load_existing_model_config(
    variables, config: Dict[str, Any], path: str = "./logs/", opt_state: Any = None
):
    """Warm start when Training.continue is set (reference model.py:57-60)."""
    if config.get("continue", 0):
        model_name = config.get("startfrom", "existing_model")
        return load_existing_model(variables, model_name, path, opt_state)
    return variables, opt_state


def checkpoint_exists(model_name: str, path: str = "./logs/") -> bool:
    return os.path.exists(os.path.join(path, model_name, model_name + ".pk"))


def load_checkpoint_meta(model_name: str, path: str = "./logs/") -> Dict[str, Any]:
    """Training-progress metadata stored alongside the weights ({} for
    checkpoints written before meta existed, or when none was saved)."""
    path_name = os.path.join(path, model_name, model_name + ".pk")
    _version, payload = read_checkpoint_payload(path_name)
    return payload.get("meta") or {}


# -------------------------------------------------- elastic world handoff
# (graftelastic, docs/DISTRIBUTED.md "Elastic runbook"): a checkpoint written
# at world size N must restore at world size M. The payload side is already
# world-independent by construction — params/opt_state are replicated, the
# param-tree fingerprint has no world component — so the handoff contract
# lives entirely in the meta block these helpers write and verify.

ELASTIC_META_KEY = "elastic"


def elastic_handoff_meta(
    world_size: int,
    epoch: int,
    cursor: int,
    incarnation: int,
    global_step: int,
    num_batches: int,
) -> Dict[str, Any]:
    """The meta block an elastic save carries: the GLOBAL epoch cursor (which
    batch of the epoch's world-independent plan to resume at), the world the
    save happened under (diagnostic only — never a restore constraint), and
    the incarnation/step counters the drills assert on."""
    return {
        "world_size": int(world_size),
        "epoch": int(epoch),
        "cursor": int(cursor),
        "incarnation": int(incarnation),
        "global_step": int(global_step),
        "num_batches": int(num_batches),
    }


def verify_elastic_handoff(
    meta: Dict[str, Any],
    new_world: int,
    min_workers: int = 1,
    max_workers: Optional[int] = None,
) -> Dict[str, Any]:
    """World-size-independent handoff assertions, run at every elastic
    restore: the NEW world must satisfy the configured range, and the
    checkpoint's elastic block (when present) must carry a coherent resume
    position. A checkpoint without the block (a plain periodic save) hands
    off at the epoch boundary — ``cursor`` 0 — which is exactly the
    pre-elastic resume contract. Raises :class:`CheckpointError` naming both
    worlds on a violation; returns the resume descriptor
    ``{epoch, cursor, world_size, global_step}``."""
    new_world = int(new_world)
    if new_world < 1:
        raise CheckpointError(
            f"elastic handoff: new world size {new_world} is not a positive "
            "worker count"
        )
    if new_world < int(min_workers) or (
        max_workers is not None and new_world > int(max_workers)
    ):
        raise CheckpointError(
            f"elastic handoff: new world size {new_world} outside the "
            f"configured range [{min_workers}, {max_workers}]"
        )
    block = (meta or {}).get(ELASTIC_META_KEY)
    if not block:
        return {
            "epoch": int((meta or {}).get("epoch") or 0),
            "cursor": 0,
            "world_size": None,
            "global_step": None,
        }
    try:
        epoch = int(block["epoch"])
        cursor = int(block["cursor"])
        saved_world = int(block["world_size"])
        num_batches = int(block.get("num_batches", 0))
    except (KeyError, TypeError, ValueError) as e:
        raise CheckpointError(
            f"elastic handoff: checkpoint elastic block is malformed "
            f"({e!r}) — saved under world_size="
            f"{(block or {}).get('world_size')!r}, restoring at world_size="
            f"{new_world}"
        ) from e
    if epoch < 0 or cursor < 0 or (num_batches and cursor > num_batches):
        raise CheckpointError(
            f"elastic handoff: resume position epoch={epoch} cursor={cursor} "
            f"(of {num_batches} batches) is incoherent — checkpoint saved "
            f"under world_size={saved_world}, restoring at world_size="
            f"{new_world}"
        )
    return {
        "epoch": epoch,
        "cursor": cursor,
        "world_size": saved_world,
        "global_step": block.get("global_step"),
    }


# ------------------------------------------------------- migration utilities


def update_checkpoint_meta(path_name: str, meta: Dict[str, Any]) -> None:
    """Rewrite one checkpoint's meta section in place (atomic), re-encoding
    as v2 whatever the source format was. Test harnesses use this to install
    mid-run resume states; operators use it for history surgery."""
    _version, payload = read_checkpoint_payload(path_name)
    sections = {
        "params": payload["params"],
        "batch_stats": payload["batch_stats"],
        "opt_state": payload.get("opt_state"),
        "meta": ckpt_format.pack_meta(meta),
    }
    header = dict(payload.get("header") or {})
    header.pop("format_version", None)
    header["epoch"] = (meta or {}).get("epoch")
    header["step"] = (meta or {}).get("step")
    write_checkpoint_blob(path_name, ckpt_format.encode(sections, header))


def migrate_checkpoint(path_name: str) -> bool:
    """v1 pickle → v2 verified container, in place (atomic). Returns True
    when the file was migrated, False when it already was v2."""
    version, payload = read_checkpoint_payload(path_name)
    if version >= ckpt_format.FORMAT_VERSION:
        return False
    sections = {
        "params": payload["params"],
        "batch_stats": payload["batch_stats"],
        "opt_state": payload.get("opt_state"),
        "meta": ckpt_format.pack_meta(payload.get("meta") or {}),
    }
    header = {
        "epoch": (payload.get("meta") or {}).get("epoch"),
        "step": (payload.get("meta") or {}).get("step"),
        "migrated_from": 1,
    }
    write_checkpoint_blob(path_name, ckpt_format.encode(sections, header))
    return True


def migrate_run_dir(run_dir: str) -> Dict[str, List[str]]:
    """Migrate every ``*.pk`` checkpoint in a run directory. Returns
    {migrated: [...], already_v2: [...], failed: [...]}. Corrupt files are
    left untouched (the fallback chain, not migration, handles those)."""
    out: Dict[str, List[str]] = {"migrated": [], "already_v2": [], "failed": []}
    for p in sorted(glob.glob(os.path.join(run_dir, "*.pk"))):
        try:
            out["migrated" if migrate_checkpoint(p) else "already_v2"].append(p)
        except CheckpointError:
            out["failed"].append(p)
    return out
