"""Checkpoint container format v2 — integrity-checked msgpack
(docs/CHECKPOINTING.md "Format").

A v2 checkpoint is ``MAGIC`` followed by one msgpack map::

    {
      "format_version": 2,
      "header": {
        "format_version": 2,
        "epoch": <int|None>,          # from the save's meta, for cheap triage
        "step": <int|None>,
        "param_fingerprint": <hex>,   # sha256 over the param-tree structure
        "sections": [<name>, ...],
      },
      "digests":  {<section>: <sha256 hex>, ...},
      "sections": {<section>: <bytes>, ...},
    }

Sections are opaque byte blobs (``flax.serialization.to_bytes`` for
params/batch_stats/opt_state, msgpack for meta). Every load recomputes each
section's sha256 and compares against ``digests`` — a bit-flip, a torn write,
or a truncation surfaces as :class:`CheckpointCorruptError` BEFORE any
deserializer touches the bytes. The container itself is msgpack, never
pickle: loading a v2 checkpoint executes no code.

The encoding is deliberately wall-clock-free (timestamps live in the
retention manifest, not the file): serializing the same state twice — or
once synchronously and once through the async writer — produces identical
bytes, which the async/sync byte-identity tests assert.

v1 files (the legacy pickle payload) are detected by the absence of
``MAGIC``; read-compat lives in :mod:`.io`.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np

MAGIC = b"HGNN2\x00"
FORMAT_VERSION = 2

#: The one-line migration command named by the v1 deprecation warning and
#: the corruption-triage docs.
MIGRATE_CMD = "python -m hydragnn_tpu.checkpoint migrate <logs/run_dir>"


class CheckpointError(RuntimeError):
    """Base class for checkpoint-subsystem failures."""


class CheckpointCorruptError(CheckpointError):
    """A checkpoint file failed integrity verification (bad magic, torn or
    truncated container, per-section digest mismatch, undecodable legacy
    pickle). The fallback chain treats exactly this class as 'try the next
    retained entry'."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


class CheckpointChainExhaustedError(CheckpointError):
    """Every candidate in the fallback chain (latest + all retained entries)
    failed verification. Carries the per-file failure list for the loud
    final error the supervisor surfaces."""

    def __init__(self, run_dir: str, failures: List[Dict[str, str]]):
        detail = "; ".join(f"{f['file']}: {f['reason']}" for f in failures)
        super().__init__(
            f"checkpoint fallback chain exhausted in {run_dir} "
            f"({len(failures)} candidate(s) failed): {detail}"
        )
        self.run_dir = run_dir
        self.failures = failures


def _msgpack_default(obj):
    """Meta dicts may carry numpy scalars/arrays (loss history, scheduler
    state); coerce them to plain types so meta stays msgpack-only."""
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    raise TypeError(f"meta value of type {type(obj).__name__} is not msgpack-encodable")


def pack_meta(meta: Optional[Dict[str, Any]]) -> bytes:
    return msgpack.packb(meta or {}, use_bin_type=True, default=_msgpack_default)


def unpack_meta(blob: bytes) -> Dict[str, Any]:
    return msgpack.unpackb(blob, raw=False, strict_map_key=False) or {}


def param_fingerprint(params) -> str:
    """sha256 over the param tree's STRUCTURE (key paths, shapes, dtypes) —
    cheap to compute from a template without touching weight bytes. A
    mismatch means the checkpoint belongs to a different model/config, which
    is an operator error, not corruption: the fallback chain does NOT mask
    it (every retained entry would mismatch identically)."""
    import jax

    paths = jax.tree_util.tree_flatten_with_path(params)[0]
    desc = ";".join(
        f"{jax.tree_util.keystr(kp)}:{tuple(getattr(leaf, 'shape', ()))}"
        f":{getattr(leaf, 'dtype', '?')}"
        for kp, leaf in paths
    )
    return hashlib.sha256(desc.encode()).hexdigest()


def content_identity(blob: bytes, path: str = "<bytes>") -> Tuple[str, Dict[str, Any]]:
    """Digest-verified CONTENT identity of one v2 checkpoint blob →
    ``(identity_hex, header)``. The identity is sha256 over the sorted
    per-section digest map (header blob included), so two checkpoints share
    an identity iff their verified bytes agree section for section — the
    model-version identity of the lifecycle layer (docs/CHECKPOINTING.md
    "Version identity"; ``param_fingerprint`` deliberately cannot serve
    here: it hashes the tree STRUCTURE, which every retrain of the same
    architecture shares). Raises :class:`CheckpointCorruptError` exactly
    like :func:`decode` — an identity is only ever computed over bytes that
    verified."""
    header, sections = decode(blob, path)
    digests = {k: hashlib.sha256(v).hexdigest() for k, v in sections.items()}
    desc = ";".join(f"{k}:{v}" for k, v in sorted(digests.items()))
    return hashlib.sha256(desc.encode()).hexdigest(), header


def file_content_identity(path: str) -> Tuple[str, Dict[str, Any]]:
    """:func:`content_identity` of a checkpoint FILE (reads + verifies).
    Unreadable files surface as :class:`CheckpointCorruptError` so callers
    have one failure class for 'this is not a loadable version'."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        raise CheckpointCorruptError(path, f"unreadable ({e})") from e
    return content_identity(blob, path)


def encode(
    sections: Dict[str, Optional[bytes]], header: Optional[Dict[str, Any]] = None
) -> bytes:
    """Serialize sections into the v2 container. ``None`` sections (an
    absent opt_state) are dropped, matching the v1 payload's ``None``.
    The header is stored as its own msgpack blob with a digest of its own,
    so EVERY meaningful region of the file is integrity-protected: a flip in
    a section trips that section's digest, a flip in the header blob trips
    the header digest, and a flip in the container framing itself makes the
    outer msgpack undecodable — all three surface as
    :class:`CheckpointCorruptError`, never as silently-altered state."""
    present = {k: v for k, v in sections.items() if v is not None}
    head = dict(header or {})
    head["format_version"] = FORMAT_VERSION
    head["sections"] = sorted(present)
    header_blob = msgpack.packb(head, use_bin_type=True, default=_msgpack_default)
    digests = {k: hashlib.sha256(v).hexdigest() for k, v in present.items()}
    digests["__header__"] = hashlib.sha256(header_blob).hexdigest()
    doc = {
        "format_version": FORMAT_VERSION,
        "header": header_blob,
        "digests": digests,
        "sections": present,
    }
    return MAGIC + msgpack.packb(doc, use_bin_type=True, default=_msgpack_default)


def is_v2_blob(head: bytes) -> bool:
    return head[: len(MAGIC)] == MAGIC


def decode(
    blob: bytes, path: str = "<bytes>", verify: bool = True
) -> Tuple[Dict[str, Any], Dict[str, bytes]]:
    """Parse + verify a v2 container → (header, sections). Raises
    :class:`CheckpointCorruptError` on bad magic, an unparseable/truncated
    container, a missing digest, or any digest mismatch."""
    if not is_v2_blob(blob):
        raise CheckpointCorruptError(path, "bad magic (not a v2 checkpoint)")
    try:
        doc = msgpack.unpackb(blob[len(MAGIC):], raw=False, strict_map_key=False)
    except Exception as e:  # truncated/torn container
        raise CheckpointCorruptError(
            path, f"container undecodable ({type(e).__name__}: {e})"
        ) from e
    if not isinstance(doc, dict) or "sections" not in doc:
        raise CheckpointCorruptError(path, "container missing sections map")
    sections = doc["sections"]
    digests = doc.get("digests") or {}
    header_blob = doc.get("header") or b""
    if verify:
        checks = dict(sections)
        checks["__header__"] = header_blob
        for name, payload in checks.items():
            want = digests.get(name)
            if want is None:
                raise CheckpointCorruptError(path, f"section {name!r} has no digest")
            got = hashlib.sha256(payload).hexdigest()
            if got != want:
                raise CheckpointCorruptError(
                    path,
                    f"digest mismatch in section {name!r} "
                    f"(stored {want[:12]}…, computed {got[:12]}…)",
                )
    try:
        header = msgpack.unpackb(header_blob, raw=False, strict_map_key=False) or {}
    except Exception as e:
        raise CheckpointCorruptError(
            path, f"header undecodable ({type(e).__name__}: {e})"
        ) from e
    # Version authority is the DIGEST-VERIFIED header copy, never the outer
    # framing field (which no digest covers — a flipped byte there must not
    # masquerade as a too-new file and bypass the fallback chain; the outer
    # copy is advisory/fast-sniff only). Reaching here means the digests
    # verified, so a too-new version is a genuine, intact newer file: fail
    # loudly (upgrade, don't silently lose epochs to a fallback walk).
    version = header.get("format_version")
    if not isinstance(version, int) or not (1 <= version <= FORMAT_VERSION):
        raise CheckpointError(
            f"{path}: format_version {version!r} is outside this build's "
            f"supported range [1, {FORMAT_VERSION}] — upgrade hydragnn_tpu "
            "to load it"
        )
    return header, sections
