"""graftpilot — fleet autopilot (docs/SERVING.md "Fleet autopilot";
ROADMAP item 2).

Predictive autoscaling with hysteresis, a brownout degradation ladder,
and tenant-isolation bulkheads over the graftroute multi-replica tier:

  autopilot.py  the ``hydragnn-pilot`` control loop — one locked sensor
                read (``Router.control_snapshot``), a reactive arm on the
                shared ``Hysteresis`` dead-band machine (flywheel/drift),
                a predictive arm fit from streaming size-histogram deltas,
                scale-to-zero + warm cold-wake through graftcache;
  brownout.py   ordered reversible degradation (shed the lowest class →
                tighten deadlines → shrink the queue), walked under the
                same no-flap hysteresis discipline;
  tenants.py    per-tenant in-flight quotas + retry-budget token buckets,
                shed as tenant-tagged 429s before fleet capacity is spent;
  metrics.py    the ``hydragnn_pilot_*`` Prometheus family.

Drills: ``python benchmarks/bench.py --pilot`` (flash crowd, tenant
isolation, scale-to-zero/cold-wake, kill-under-autoscale) →
``benchmarks/PILOT_r*.json``.
"""

from .autopilot import Autopilot, AutopilotConfig
from .brownout import STEP_SEVERITY, BrownoutLadder, parse_ladder
from .metrics import PilotMetrics
from .tenants import TenantBulkheads

__all__ = [
    "STEP_SEVERITY",
    "Autopilot",
    "AutopilotConfig",
    "BrownoutLadder",
    "PilotMetrics",
    "TenantBulkheads",
    "parse_ladder",
]
