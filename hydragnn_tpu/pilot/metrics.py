"""Autopilot metrics: the ``hydragnn_pilot_*`` Prometheus family
(docs/OBSERVABILITY.md "Prometheus catalogue", docs/SERVING.md "Fleet
autopilot").

Same design as the router's ``RouteMetrics``: host-side, one instrumented
lock, counters + gauges + a per-tenant table. Observations arrive from the
``hydragnn-pilot`` control thread (ticks, scale/brownout decisions) and
from every router caller thread that crosses a tenant bulkhead
(pilot/tenants.py quota sheds and retry denials) — all fields are declared
guarded and graftrace-checked.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..analysis import tsan


class PilotMetrics:
    """All counters/gauges of one ``Autopilot`` (+ its tenant bulkheads)."""

    _COUNTERS = (
        "ticks_total",
        "scale_up_total",
        "scale_down_total",
        "predictive_scale_up_total",
        "cold_wake_total",
        "scale_to_zero_total",
        "replace_total",
        "reap_total",
        "brownout_step_total",
        "brownout_recover_total",
        "tenant_shed_total",
        "tenant_retry_denied_total",
    )
    _GAUGES = (
        "target_replicas",
        "brownout_level",
        "pressure",
        "rate_rps",
    )

    def __init__(self):
        self._lock = tsan.instrument_lock(
            threading.Lock(), "PilotMetrics._lock"
        )
        self.ticks_total = 0  # guarded-by: self._lock
        self.scale_up_total = 0  # guarded-by: self._lock
        self.scale_down_total = 0  # guarded-by: self._lock
        self.predictive_scale_up_total = 0  # guarded-by: self._lock
        self.cold_wake_total = 0  # guarded-by: self._lock
        self.scale_to_zero_total = 0  # guarded-by: self._lock
        self.replace_total = 0  # guarded-by: self._lock
        self.reap_total = 0  # guarded-by: self._lock
        self.brownout_step_total = 0  # guarded-by: self._lock
        self.brownout_recover_total = 0  # guarded-by: self._lock
        self.tenant_shed_total = 0  # guarded-by: self._lock
        self.tenant_retry_denied_total = 0  # guarded-by: self._lock
        self.target_replicas = 0.0  # guarded-by: self._lock
        self.brownout_level = 0.0  # guarded-by: self._lock
        self.pressure = 0.0  # guarded-by: self._lock
        self.rate_rps = 0.0  # guarded-by: self._lock
        # Per tenant: quota sheds + retry denials (the tenant-tagged 429
        # evidence an operator needs to name the noisy tenant).
        self._per_tenant: Dict[str, Dict[str, int]] = {}  # guarded-by: self._lock

    # ------------------------------------------------------------- recorders
    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, name, getattr(self, name) + n)
            tsan.shared_access("PilotMetrics.counters")

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            setattr(self, name, float(value))

    def count_tenant(self, tenant: str, which: str, n: int = 1) -> None:
        with self._lock:
            entry = self._per_tenant.setdefault(
                str(tenant), {"shed": 0, "retry_denied": 0}
            )
            entry[which] = entry.get(which, 0) + n

    def read_counters(self, *names: str) -> Dict[str, float]:
        """One locked copy of the named counters/gauges (same torn-pair
        contract as ServeMetrics/RouteMetrics.read_counters)."""
        with self._lock:
            return {n: getattr(self, n) for n in names}

    # -------------------------------------------------------------- reporters
    def snapshot(self) -> Dict:
        with self._lock:
            out: Dict = {n: getattr(self, n) for n in self._COUNTERS}
            out.update({n: getattr(self, n) for n in self._GAUGES})
            out["per_tenant"] = {
                k: dict(v) for k, v in sorted(self._per_tenant.items())
            }
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition — rides the router /metrics payload
        when an autopilot is attached."""
        p = "hydragnn_pilot"
        snap = self.snapshot()
        lines = []
        for name in self._COUNTERS:
            lines.append(f"# TYPE {p}_{name} counter")
            lines.append(f"{p}_{name} {snap[name]}")
        for name in self._GAUGES:
            lines.append(f"# TYPE {p}_{name} gauge")
            lines.append(f"{p}_{name} {snap[name]}")
        lines.append(f"# TYPE {p}_tenant_shed_total counter")
        for tenant, c in snap["per_tenant"].items():
            lines.append(
                f'{p}_tenant_shed_total{{tenant="{tenant}"}} {c["shed"]}'
            )
        lines.append(f"# TYPE {p}_tenant_retry_denied_total counter")
        for tenant, c in snap["per_tenant"].items():
            lines.append(
                f'{p}_tenant_retry_denied_total{{tenant="{tenant}"}} '
                f"{c['retry_denied']}"
            )
        return "\n".join(lines) + "\n"
