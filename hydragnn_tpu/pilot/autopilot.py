"""graftpilot — the fleet autopilot control loop (docs/SERVING.md "Fleet
autopilot"; ROADMAP item 2).

One daemon thread (``hydragnn-pilot``) closes the loop between the
router's sensors and its actuators:

  sense   Router.control_snapshot() — ONE locked read of queue depth,
          per-class sheds, rolling fleet p99 vs SLO deadlines, and
          per-replica lifecycle states (satellite: the torn-counter-pair
          reasoning from the PR-8 scrape bug, applied to a control input);
  decide  three coupled arms —
            * reactive autoscaler: pressure through a ``Hysteresis``
              dead-band (the SAME machine the flywheel's DriftDetector
              runs on — flywheel/drift.py) with a cooldown floored at the
              measured replica spin-up wall, so the loop cannot flap or
              re-fire while a previous spin-up is still warming;
            * predictive autoscaler: demand rate from streaming
              size-histogram deltas, least-squares slope over a short
              window, scale when the rate *projected one spin-up wall
              ahead* exceeds fleet capacity — ahead of the wave, not
              behind it;
            * brownout ladder (brownout.py): ordered reversible
              degradation while capacity catches up;
  act     Router.scale_up (warm, through the shared graftcache store —
          a woken replica does ZERO XLA compiles), Router.scale_down →
          reap_retired (drain without dropping in-flight work), and
          replacement of ejected corpses.

Scale-to-zero: with ``min_replicas=0`` and sustained zero traffic the
pilot retires the whole fleet; the first request after that fails fast
(503, retryable) and its failure is the cold-wake signal — the next tick
spins a replica from the warm cache, bypassing the cooldown.

Determinism for tests/drills: ``tick(now=...)`` injects the clock and the
loop thread is optional — exactly the flywheel's discipline. Engine
closes NEVER happen on the pilot (or health) thread: retired/ejected
replicas accumulate and are closed by ``close_retired()`` / ``stop()`` on
the caller's thread (an engine close joins worker threads).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Iterable, List, Optional, Tuple

from ..analysis import tsan
from ..flywheel.drift import Hysteresis
from ..route.replica import Replica
from ..route.router import ADMITTED, DRAINING, EJECTED, WARMING, Router
from ..telemetry import graftel as telemetry
from .brownout import BrownoutLadder, parse_ladder
from .metrics import PilotMetrics
from .tenants import TenantBulkheads


@dataclass
class AutopilotConfig:
    """Tunables for one autopilot. ``__post_init__`` enforces at runtime
    exactly what ``contracts._check_pilot`` flags statically (``bad-pilot``
    findings) — a config that passes the gate constructs, one that fails
    it raises here too."""

    # Reactive arm: pressure watermarks (dead band) + sustain + cooldown.
    scale_high: float = 0.85
    scale_low: float = 0.3
    sustain_up: int = 2
    sustain_down: int = 8
    cooldown_s: float = 3.0
    # The measured (or assumed) replica spin-up wall. The cooldown must
    # cover it: re-deciding while the previous decision is still warming
    # double-scales on every wave.
    spinup_wall_s: float = 2.0
    min_replicas: int = 1
    max_replicas: int = 4
    # Capacity model: in-flight slots one replica handles comfortably.
    per_replica_inflight: int = 4
    # Predictive arm.
    predictive: bool = True
    predict_window: int = 8
    predict_lead_s: float = 0.5
    per_replica_rps: float = 50.0
    # Scale-to-zero: retire the whole fleet after this many consecutive
    # zero-traffic ticks (0 disables; requires min_replicas == 0).
    idle_ticks_to_zero: int = 0
    # Brownout ladder.
    brownout_high: float = 1.5
    brownout_low: float = 0.5
    brownout_sustain: int = 2
    ladder: Tuple[str, ...] = (
        "shed_class:ensemble",
        "tighten_deadlines:0.5",
        "shrink_queue:8",
    )
    # Tenant bulkheads (0 quota disables them entirely).
    tenant_inflight_quota: int = 0
    tenant_retry_budget: int = 16
    tenant_retry_refill_per_s: float = 8.0
    # The global bound a per-tenant quota must stay inside: one tenant's
    # bulkhead must never be wide enough to fill the whole fleet.
    global_inflight_limit: int = 64
    per_tenant: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # Ejected corpses are reaped (removed + closed) after this many ticks
    # of grace — long enough for /healthz post-mortems, short enough that
    # the table doesn't grow without bound.
    eject_grace_ticks: int = 10
    tick_interval_s: float = 0.25

    def __post_init__(self):
        if not (0 <= float(self.scale_low) < float(self.scale_high)):
            raise ValueError(
                "scale watermarks need 0 <= scale_low < scale_high, got "
                f"low={self.scale_low} high={self.scale_high}"
            )
        if not (0 <= float(self.brownout_low) < float(self.brownout_high)):
            raise ValueError(
                "brownout watermarks need 0 <= low < high, got "
                f"low={self.brownout_low} high={self.brownout_high}"
            )
        if float(self.cooldown_s) < float(self.spinup_wall_s):
            raise ValueError(
                f"cooldown_s ({self.cooldown_s}) must cover the spin-up "
                f"wall ({self.spinup_wall_s}): re-deciding while the last "
                "replica is still warming double-scales every wave"
            )
        if int(self.min_replicas) < 0 or int(self.max_replicas) < 1:
            raise ValueError(
                f"need min_replicas >= 0 and max_replicas >= 1, got "
                f"{self.min_replicas}/{self.max_replicas}"
            )
        if int(self.min_replicas) > int(self.max_replicas):
            raise ValueError(
                f"min_replicas ({self.min_replicas}) > max_replicas "
                f"({self.max_replicas})"
            )
        if int(self.sustain_up) < 1 or int(self.sustain_down) < 1:
            raise ValueError("sustain_up/sustain_down must be >= 1")
        if int(self.per_replica_inflight) < 1:
            raise ValueError("per_replica_inflight must be >= 1")
        if float(self.per_replica_rps) <= 0:
            raise ValueError("per_replica_rps must be > 0")
        if int(self.predict_window) < 2:
            raise ValueError("predict_window must be >= 2")
        if int(self.idle_ticks_to_zero) > 0 and int(self.min_replicas) != 0:
            raise ValueError(
                "idle_ticks_to_zero needs min_replicas == 0 "
                "(scale-to-zero retires the whole fleet)"
            )
        if int(self.tenant_inflight_quota) < 0:
            raise ValueError("tenant_inflight_quota must be >= 0")
        if int(self.tenant_inflight_quota) > int(self.global_inflight_limit):
            raise ValueError(
                f"tenant_inflight_quota ({self.tenant_inflight_quota}) "
                f"exceeds global_inflight_limit "
                f"({self.global_inflight_limit}): one tenant could fill "
                "the whole fleet — no bulkhead at all"
            )
        if float(self.tick_interval_s) <= 0:
            raise ValueError("tick_interval_s must be > 0")
        parse_ladder(self.ladder)  # empty/unknown/unordered raise here

    def to_json(self) -> Dict[str, Any]:
        return {
            "scale_high": self.scale_high,
            "scale_low": self.scale_low,
            "sustain_up": self.sustain_up,
            "sustain_down": self.sustain_down,
            "cooldown_s": self.cooldown_s,
            "spinup_wall_s": self.spinup_wall_s,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "per_replica_inflight": self.per_replica_inflight,
            "predictive": self.predictive,
            "predict_window": self.predict_window,
            "predict_lead_s": self.predict_lead_s,
            "per_replica_rps": self.per_replica_rps,
            "idle_ticks_to_zero": self.idle_ticks_to_zero,
            "brownout_high": self.brownout_high,
            "brownout_low": self.brownout_low,
            "brownout_sustain": self.brownout_sustain,
            "ladder": list(self.ladder),
            "tenant_inflight_quota": self.tenant_inflight_quota,
            "tenant_retry_budget": self.tenant_retry_budget,
            "tenant_retry_refill_per_s": self.tenant_retry_refill_per_s,
            "global_inflight_limit": self.global_inflight_limit,
            "eject_grace_ticks": self.eject_grace_ticks,
            "tick_interval_s": self.tick_interval_s,
        }


class Autopilot:
    """The control loop. ``factory(name) -> Replica`` builds a new replica
    (pointed at the shared graftcache store, so spin-ups are warm);
    ``histogram_sources`` yields objects exposing ``histogram_json()``
    (graftstream size-histogram telemetry) whose weight deltas are the
    predictive arm's demand signal — without sources the arm falls back to
    the fleet's own request-counter deltas (reactive-ish, but still
    slope-projected)."""

    def __init__(
        self,
        router: Router,
        factory: Callable[[str], Replica],
        config: Optional[AutopilotConfig] = None,
        histogram_sources: Iterable[Any] = (),
        metrics: Optional[PilotMetrics] = None,
        name_prefix: str = "pilot",
    ):
        self.router = router
        self.factory = factory
        self.config = config if config is not None else AutopilotConfig()
        self.metrics = metrics if metrics is not None else PilotMetrics()
        self.histogram_sources = histogram_sources
        self.name_prefix = str(name_prefix)
        cfg = self.config
        self.ladder = BrownoutLadder(
            router,
            cfg.ladder,
            high=cfg.brownout_high,
            low=cfg.brownout_low,
            sustain=cfg.brownout_sustain,
            metrics=self.metrics,
        )
        self.bulkheads: Optional[TenantBulkheads] = None
        if cfg.tenant_inflight_quota > 0:
            self.bulkheads = TenantBulkheads(
                inflight_quota=cfg.tenant_inflight_quota,
                retry_budget=cfg.tenant_retry_budget,
                retry_refill_per_s=cfg.tenant_retry_refill_per_s,
                per_tenant=cfg.per_tenant,
                metrics=self.metrics,
            )
            router.set_bulkheads(self.bulkheads)

        self._lock = tsan.instrument_lock(threading.Lock(), "Autopilot._lock")
        # Reactive dead-band machine — same external-guard discipline as
        # DriftDetector's (not internally locked; all touches below hold
        # self._lock).
        self._scale = Hysteresis(  # guarded-by: self._lock
            cfg.scale_high, cfg.scale_low, cfg.sustain_up
        )
        self._under = 0  # consecutive ticks below scale_low  # guarded-by: self._lock
        self._idle = 0  # consecutive zero-traffic ticks  # guarded-by: self._lock
        self._spawned = 0  # pilot-N name counter  # guarded-by: self._lock
        self._last_scale_t: Optional[float] = None  # guarded-by: self._lock
        self._last_tick_t: Optional[float] = None  # guarded-by: self._lock
        # Demand-rate samples (ts, rps) for the predictive least-squares.
        self._rate_samples: Deque[Tuple[float, float]] = deque(  # guarded-by: self._lock
            maxlen=int(cfg.predict_window)
        )
        # Cumulative histogram weight last seen per source (id()).
        self._hist_seen: Dict[int, int] = {}  # guarded-by: self._lock
        # Previous control-snapshot counters (delta base).
        self._prev_counters: Dict[str, float] = {}  # guarded-by: self._lock
        # Ejected corpses: name -> ticks since first seen ejected.
        self._eject_age: Dict[str, int] = {}  # guarded-by: self._lock
        # Replicas retired/reaped but not yet closed (engine closes join
        # worker threads — they run on the CALLER thread, never this one).
        self._to_close: List[Replica] = []  # guarded-by: self._lock
        self._last: Dict[str, Any] = {}  # last tick summary  # guarded-by: self._lock

        # Desired fleet size, seeded from what's live right now.
        snap = router.control_snapshot()
        live = snap["counts"].get(ADMITTED, 0) + snap["counts"].get(WARMING, 0)
        self._target = max(  # guarded-by: self._lock
            cfg.min_replicas, min(cfg.max_replicas, live)
        )
        self.metrics.set_gauge("target_replicas", self._target)

        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---------------------------------------------------------------- loop
    def start(self) -> "Autopilot":
        """Launch the pilot thread (idempotent)."""
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._loop, name="hydragnn-pilot", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0, clear_degradation: bool = True) -> None:
        """Stop the loop, clear any brownout residue, and close every
        replica the pilot retired (on THIS thread)."""
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if clear_degradation:
            self.ladder.reset()
        self.close_retired()

    def _loop(self) -> None:
        ctx = telemetry.new_context()
        telemetry.attach(ctx)
        while not self._stop_evt.is_set():
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                telemetry.event("pilot/tick_error", error=repr(e))
            self._stop_evt.wait(self.config.tick_interval_s)

    # ---------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One control iteration. ``now`` (monotonic seconds) is injectable
        so tests and drills can step deterministically."""
        cfg = self.config
        t = time.monotonic() if now is None else float(now)
        snap = self.router.control_snapshot()
        deltas = self._counter_deltas(snap)
        rate = self._observe_rate(snap, deltas, t)
        pressure = self._pressure(snap, deltas)

        actions: List[str] = []
        spawn_reason: Optional[str] = None
        with self._lock:
            live = snap["counts"].get(ADMITTED, 0) + snap["counts"].get(
                WARMING, 0
            )
            target = self._target
            cooled = (
                self._last_scale_t is None
                or (t - self._last_scale_t) >= cfg.cooldown_s
            )

            # --- reactive arm: hysteresis dead band + cooldown. Capacity
            # is added on the sustained-entry transition, and again each
            # cooldown while pressure still sits AT/ABOVE the high
            # watermark — in the dead band the active state only vetoes
            # scale-down, it never adds replicas (no creep).
            trans = self._scale.step(pressure)
            saturated = trans == "entered" or (
                self._scale.active and pressure >= cfg.scale_high
            )
            if saturated and cooled and target < cfg.max_replicas:
                target += 1
                self._last_scale_t = t
                spawn_reason = "reactive"
                actions.append("scale_up:reactive")
            # --- predictive arm: only when the reactive arm is quiet.
            elif (
                cfg.predictive
                and cooled
                and target < cfg.max_replicas
                and target > 0
            ):
                predicted = self._predicted_rate(cfg)
                if (
                    predicted is not None
                    and predicted > target * cfg.per_replica_rps
                ):
                    target += 1
                    self._last_scale_t = t
                    spawn_reason = "predictive"
                    actions.append("scale_up:predictive")

            # --- scale-down: long sustained calm, opposite watermark.
            if pressure < cfg.scale_low and not self._scale.active:
                self._under += 1
            else:
                self._under = 0
            if (
                spawn_reason is None
                and self._under >= cfg.sustain_down
                and cooled
                and target > cfg.min_replicas
            ):
                target -= 1
                self._last_scale_t = t
                self._under = 0
                actions.append("scale_down")

            # --- scale-to-zero on sustained idle.
            idle_now = rate == 0.0 and snap["queue_depth"] == 0
            self._idle = self._idle + 1 if idle_now else 0
            if (
                cfg.idle_ticks_to_zero > 0
                and self._idle >= cfg.idle_ticks_to_zero
                and target > 0
                and cfg.min_replicas == 0
            ):
                target = 0
                self._last_scale_t = t
                actions.append("scale_to_zero")

            # --- cold wake: fleet at zero but traffic arrived. The failed
            # request IS the wake signal; bypasses the cooldown.
            if target == 0 and live == 0 and (
                deltas.get("failed_total", 0) > 0 or snap["queue_depth"] > 0
            ):
                target = 1
                self._last_scale_t = t
                self._idle = 0
                actions.append("cold_wake")

            self._target = target
            deficit = target - live

        # --- actuate (OUTSIDE self._lock: router calls take the router
        # lock; keeping the two locks un-nested keeps the order trivial).
        spawned, retired = self._reconcile(deficit, snap, actions, spawn_reason)
        reaped = self._reap(snap)
        bstep = self.ladder.step(pressure)
        if bstep is not None:
            actions.append(f"brownout:{bstep}")

        self.metrics.count("ticks_total")
        self.metrics.set_gauge("target_replicas", target)
        self.metrics.set_gauge("pressure", pressure)
        self.metrics.set_gauge("rate_rps", rate)
        summary = {
            "ts": t,
            "pressure": round(pressure, 4),
            "rate_rps": round(rate, 3),
            "target": target,
            "live": live,
            "actions": actions,
            "spawned": spawned,
            "retired": retired,
            "reaped": reaped,
            "brownout_level": self.ladder.level,
            "queue_depth": snap["queue_depth"],
        }
        with self._lock:
            self._last = summary
        return summary

    # -------------------------------------------------------------- sensing
    def _counter_deltas(self, snap: Dict[str, Any]) -> Dict[str, float]:
        """Per-tick deltas of every fleet counter (first tick -> all 0)."""
        cur = snap["counters"]
        with self._lock:
            prev = self._prev_counters
            self._prev_counters = dict(cur)
        return {k: v - prev.get(k, v) for k, v in cur.items()}

    def _observe_rate(
        self, snap: Dict[str, Any], deltas: Dict[str, float], t: float
    ) -> float:
        """Demand rate (units/s) this tick: streaming size-histogram weight
        deltas when sources are wired, else the fleet's own request-counter
        delta. Appends to the predictive sample window."""
        with self._lock:
            last_t = self._last_tick_t
            self._last_tick_t = t
        elapsed = (t - last_t) if last_t is not None else None

        total = 0.0
        have_sources = False
        for src in self.histogram_sources:
            have_sources = True
            doc = (
                src.histogram_json()
                if hasattr(src, "histogram_json")
                else src()
            )
            weight = 0
            for row in doc.get("graph_sizes", ()):
                weight += int(row[-1])
            with self._lock:
                prev = self._hist_seen.get(id(src), 0)
                self._hist_seen[id(src)] = weight
            total += max(0, weight - prev)
        if not have_sources:
            total = max(0.0, deltas.get("requests_total", 0.0))

        if elapsed is None or elapsed <= 0:
            return 0.0
        rate = total / elapsed
        with self._lock:
            self._rate_samples.append((t, rate))
        return rate

    def _predicted_rate(self, cfg: AutopilotConfig) -> Optional[float]:
        """Least-squares slope over the sample window, projected one
        spin-up wall (+lead) ahead. None when the window is short, flat,
        or falling. Caller holds self._lock."""
        samples = list(self._rate_samples)
        if len(samples) < max(2, cfg.predict_window // 2):
            return None
        t0 = samples[0][0]
        xs = [s[0] - t0 for s in samples]
        ys = [s[1] for s in samples]
        n = float(len(samples))
        mx = sum(xs) / n
        my = sum(ys) / n
        var = sum((x - mx) ** 2 for x in xs)
        if var <= 0:
            return None
        slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / var
        if slope <= 0:
            return None
        horizon = cfg.spinup_wall_s + cfg.predict_lead_s
        return ys[-1] + slope * horizon

    def _pressure(
        self, snap: Dict[str, Any], deltas: Dict[str, float]
    ) -> float:
        """Scalar fleet pressure: max of (a) in-flight vs capacity, (b)
        rolling p99 vs the UNDEGRADED class deadline, (c) shed evidence
        (any admission shed this window means demand already exceeded
        capacity — floor 1.0 plus the shed fraction)."""
        cfg = self.config
        admitted = snap["counts"].get(ADMITTED, 0)
        inflight = snap["queue_depth"]
        if admitted == 0:
            # No capacity at all: saturated if anything wants service.
            wants = inflight > 0 or deltas.get("failed_total", 0) > 0
            return cfg.scale_high * 2.0 if wants else 0.0
        p_queue = inflight / float(admitted * cfg.per_replica_inflight)

        # Undegraded deadlines: the snapshot's deadlines_s are scaled by
        # the live brownout level — judging recovery against TIGHTENED
        # deadlines would hold the ladder down forever.
        scale = snap["degradation"]["deadline_scale"] or 1.0
        p_lat = 0.0
        for klass, p99 in snap["fleet_p99_s"].items():
            dl = snap["deadlines_s"].get(klass)
            if p99 is None or not dl:
                continue
            p_lat = max(p_lat, p99 / (dl / scale))

        shed_d = deltas.get("shed_total", 0.0) - deltas.get(
            "brownout_shed_total", 0.0
        )
        p_shed = 0.0
        if shed_d > 0:
            req_d = max(1.0, deltas.get("requests_total", 0.0))
            p_shed = 1.0 + min(1.0, shed_d / req_d)
        return max(p_queue, p_lat, p_shed)

    # ------------------------------------------------------------- actuation
    def _next_name(self) -> str:
        with self._lock:
            self._spawned += 1
            n = self._spawned
        return f"{self.name_prefix}-{n}"

    def _reconcile(
        self,
        deficit: int,
        snap: Dict[str, Any],
        actions: List[str],
        spawn_reason: Optional[str],
    ) -> Tuple[int, int]:
        """Drive the live fleet toward the target: spawn on deficit (warm,
        via the factory), retire the youngest pilot-spawned replicas on
        surplus."""
        spawned = retired = 0
        if deficit > 0:
            for _ in range(deficit):
                name = self._next_name()
                factory = self.factory
                self.router.scale_up(name, lambda nm=name: factory(nm))
                spawned += 1
                self.metrics.count("scale_up_total")
                if spawn_reason == "predictive":
                    self.metrics.count("predictive_scale_up_total")
                if "cold_wake" in actions:
                    self.metrics.count("cold_wake_total")
                elif spawn_reason is None and (
                    snap["counts"].get(EJECTED, 0) > 0
                    or snap["counts"].get(DRAINING, 0) > 0
                ):
                    # Deficit with no scale decision this tick: we are
                    # replacing a corpse the health loop drained/ejected.
                    self.metrics.count("replace_total")
                    actions.append(f"replace:{name}")
                telemetry.event(
                    "pilot/spawn", replica=name, reason=spawn_reason or "reconcile"
                )
        elif deficit < 0:
            victims = self._pick_victims(-deficit, snap)
            for name in victims:
                if self.router.scale_down(name):
                    retired += 1
                    self.metrics.count("scale_down_total")
                    telemetry.event("pilot/retire", replica=name)
            if "scale_to_zero" in actions and retired:
                self.metrics.count("scale_to_zero_total")
        return spawned, retired

    def _pick_victims(self, n: int, snap: Dict[str, Any]) -> List[str]:
        """Retire pilot-spawned replicas first (newest first — they carry
        the least cache warmth seniority), then the lexicographically last
        of the rest. Only admitted/warming replicas are candidates."""
        live = [
            name
            for name, rec in snap["replicas"].items()
            if rec["state"] in (ADMITTED, WARMING)
        ]
        prefix = f"{self.name_prefix}-"

        def key(name: str) -> Tuple[int, Any]:
            if name.startswith(prefix):
                suffix = name[len(prefix):]
                idx = int(suffix) if suffix.isdigit() else 0
                return (0, -idx)  # pilot-spawned, newest first
            return (1, name)

        return sorted(live, key=key)[:n]

    def _reap(self, snap: Dict[str, Any]) -> int:
        """Collect quiet retiring replicas and over-grace ejected corpses;
        closes happen later on a caller thread (close_retired)."""
        cfg = self.config
        reaped = list(self.router.reap_retired())
        # Ejected corpses: age them, then remove + queue for close. The
        # kill-under-autoscale drill's replaced replica exits here.
        to_remove: List[str] = []
        with self._lock:
            seen = set()
            for name, rec in snap["replicas"].items():
                if rec["state"] == EJECTED:
                    seen.add(name)
                    age = self._eject_age.get(name, 0) + 1
                    self._eject_age[name] = age
                    if age >= cfg.eject_grace_ticks:
                        to_remove.append(name)
            for name in list(self._eject_age):
                if name not in seen:
                    del self._eject_age[name]
        for name in to_remove:
            replica = self.router.remove_replica(name)
            if replica is not None:
                reaped.append(replica)
            with self._lock:
                self._eject_age.pop(name, None)
            telemetry.event("pilot/reap_ejected", replica=name)
        if reaped:
            self.metrics.count("reap_total", len(reaped))
            with self._lock:
                self._to_close.extend(reaped)
        return len(reaped)

    def close_retired(self) -> int:
        """Close every replica the pilot has collected. MUST run on a
        caller thread (engine closes join worker threads; running this
        under the pilot/health tick would self-join)."""
        with self._lock:
            batch = self._to_close
            self._to_close = []
        for replica in batch:
            try:
                replica.close()
            except Exception as e:  # noqa: BLE001 — best-effort teardown
                telemetry.event("pilot/close_error", error=repr(e))
        return len(batch)

    # -------------------------------------------------------------- reporters
    @property
    def target(self) -> int:
        with self._lock:
            return self._target

    def report(self) -> Dict[str, Any]:
        with self._lock:
            last = dict(self._last)
            target = self._target
            pending_close = len(self._to_close)
            scale = {
                "active": self._scale.active,
                "enters_total": self._scale.enters_total,
                "exits_total": self._scale.exits_total,
            }
        return {
            "target": target,
            "last_tick": last,
            "scale": scale,
            "brownout": self.ladder.report(),
            "bulkheads": self.bulkheads.report() if self.bulkheads else None,
            "pending_close": pending_close,
            "metrics": self.metrics.snapshot(),
            "config": self.config.to_json(),
        }
