"""Brownout degradation ladder (docs/SERVING.md "Fleet autopilot";
ROADMAP item 2).

When the fleet saturates faster than new replicas can spin up, the right
move is to degrade *gracefully and reversibly* instead of shedding
indiscriminately. The ladder is an ordered list of degradation steps,
strictly ranked by severity:

  ``shed_class:<name>``        (severity 1) refuse the named admission
                               class outright — the reserved lowest-
                               priority ``ensemble`` tier goes first;
  ``tighten_deadlines:<f>``    (severity 2) multiply every class's
                               effective admission deadline by ``f`` in
                               (0, 1) — the est-wait shed fires earlier;
  ``shrink_queue:<n>``         (severity 3) hard-cap the router's bounded
                               in-flight queue at ``n``.

Severity must be non-decreasing along the ladder (graftlint's
``bad-pilot`` finding rejects unordered ladders): you must not cap the
whole queue — which sheds the *highest*-priority class — while the
lowest-priority class is still being admitted.

Each level restates the FULL degradation (the union of steps 1..level)
through one ``Router.set_degradation`` call, so applying a level is
idempotent and recovery is exact reversal. Deepen/recover use the same
dead-band + sustain discipline as the autoscaler's ``Hysteresis``
(flywheel/drift.py) generalized to multiple levels: pressure must hold
over the high watermark for ``sustain`` consecutive observations to
deepen one step, and strictly under the low watermark for ``sustain``
observations to recover one step; between the watermarks the level holds.
An oscillating load cannot flap the ladder.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..analysis import tsan
from ..telemetry import graftel as telemetry
from .metrics import PilotMetrics

# Severity rank per step kind — ladders must be non-decreasing in this
# rank (checked here AND statically by contracts._check_pilot).
STEP_SEVERITY: Dict[str, int] = {
    "shed_class": 1,
    "tighten_deadlines": 2,
    "shrink_queue": 3,
}

LadderSpec = Sequence[Union[str, Tuple[str, object]]]


def parse_ladder(spec: LadderSpec) -> List[Tuple[str, object]]:
    """Parse/validate a ladder spec: ``("shed_class:ensemble",
    "tighten_deadlines:0.5", "shrink_queue:8")`` (or ``(kind, arg)``
    pairs). Raises ValueError on empty, unknown-kind, bad-argument, or
    severity-unordered ladders — the same conditions graftlint flags as
    ``bad-pilot`` before the process ever starts."""
    steps: List[Tuple[str, object]] = []
    for raw in spec:
        if isinstance(raw, (tuple, list)):
            if len(raw) != 2:
                raise ValueError(f"ladder step must be (kind, arg): {raw!r}")
            kind, arg = str(raw[0]).strip(), raw[1]
        else:
            kind, _, arg = str(raw).partition(":")
            kind = kind.strip()
        if kind not in STEP_SEVERITY:
            raise ValueError(
                f"unknown brownout step kind {kind!r} "
                f"(known: {sorted(STEP_SEVERITY)})"
            )
        if kind == "shed_class":
            arg = str(arg).strip()
            if not arg:
                raise ValueError("shed_class step needs a class name")
        elif kind == "tighten_deadlines":
            arg = float(arg)
            if not (0.0 < arg < 1.0):
                raise ValueError(
                    f"tighten_deadlines factor must be in (0, 1), got {arg}"
                )
        else:  # shrink_queue
            arg = int(arg)
            if arg < 1:
                raise ValueError(f"shrink_queue cap must be >= 1, got {arg}")
        steps.append((kind, arg))
    if not steps:
        raise ValueError("brownout ladder must not be empty")
    ranks = [STEP_SEVERITY[k] for k, _ in steps]
    if ranks != sorted(ranks):
        raise ValueError(
            "brownout ladder must be ordered by severity "
            f"(shed_class < tighten_deadlines < shrink_queue), got {ranks}"
        )
    return steps


class BrownoutLadder:
    """Walks a parsed ladder up/down against a pressure signal and applies
    the cumulative degradation to one router.

    ``step(pressure)`` is called from the autopilot tick (one thread);
    ``level``/``report`` may be read from anywhere — state sits under an
    instrumented lock, and the router application happens outside it
    (``set_degradation`` is an idempotent full-state restatement, so a
    racing reader of ``level`` can never observe a half-applied rung).
    """

    def __init__(
        self,
        router,
        steps: LadderSpec,
        high: float,
        low: float,
        sustain: int = 2,
        metrics: Optional[PilotMetrics] = None,
    ):
        if not (0 <= float(low) < float(high)):
            raise ValueError(
                f"brownout watermarks need 0 <= low < high, "
                f"got low={low} high={high}"
            )
        if int(sustain) < 1:
            raise ValueError(f"sustain must be >= 1, got {sustain}")
        self.router = router
        self.steps = parse_ladder(steps)
        self.high = float(high)
        self.low = float(low)
        self.sustain = int(sustain)
        self.metrics = metrics if metrics is not None else PilotMetrics()
        self._lock = tsan.instrument_lock(
            threading.Lock(), "BrownoutLadder._lock"
        )
        self._level = 0  # guarded-by: self._lock
        self._over = 0  # consecutive obs >= high  # guarded-by: self._lock
        self._under = 0  # consecutive obs < low  # guarded-by: self._lock

    # ----------------------------------------------------------------- walk
    def step(self, pressure: float) -> Optional[str]:
        """Feed one pressure observation; returns "deepened"/"recovered"
        when the level moved, else None."""
        changed: Optional[str] = None
        with self._lock:
            if pressure >= self.high:
                self._over += 1
                self._under = 0
                if self._over >= self.sustain and self._level < len(self.steps):
                    self._level += 1
                    self._over = 0
                    changed = "deepened"
            elif pressure < self.low:
                self._under += 1
                self._over = 0
                if self._under >= self.sustain and self._level > 0:
                    self._level -= 1
                    self._under = 0
                    changed = "recovered"
            else:
                # Dead band: the level holds, sustain counters reset — an
                # oscillation between the watermarks cannot flap the ladder.
                self._over = 0
                self._under = 0
            level = self._level
        if changed is not None:
            self._apply(level)
            self.metrics.count(
                "brownout_step_total"
                if changed == "deepened"
                else "brownout_recover_total"
            )
            self.metrics.set_gauge("brownout_level", level)
            telemetry.event(
                "pilot/brownout",
                direction=changed,
                level=level,
                step=self.steps[level - 1][0] if level else None,
            )
        return changed

    def _apply(self, level: int) -> None:
        """Restate the FULL degradation for steps[:level] (idempotent)."""
        shed: set = set()
        scale = 1.0
        cap: Optional[int] = None
        for kind, arg in self.steps[:level]:
            if kind == "shed_class":
                shed.add(arg)
            elif kind == "tighten_deadlines":
                scale *= float(arg)
            else:  # shrink_queue
                cap = int(arg) if cap is None else min(cap, int(arg))
        self.router.set_degradation(
            shed_classes=shed, deadline_scale=scale, queue_cap=cap
        )

    def reset(self) -> None:
        """Clear degradation entirely (autopilot stop path)."""
        with self._lock:
            self._level = 0
            self._over = 0
            self._under = 0
        self._apply(0)
        self.metrics.set_gauge("brownout_level", 0)

    # -------------------------------------------------------------- reporters
    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def report(self) -> Dict:
        with self._lock:
            level = self._level
            over, under = self._over, self._under
        return {
            "level": level,
            "max_level": len(self.steps),
            "steps": [
                {"kind": k, "arg": a, "active": i < level}
                for i, (k, a) in enumerate(self.steps)
            ],
            "high": self.high,
            "low": self.low,
            "sustain": self.sustain,
            "over": over,
            "under": under,
        }
