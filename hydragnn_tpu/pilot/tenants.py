"""Tenant-isolation bulkheads (docs/SERVING.md "Fleet autopilot";
ROADMAP item 2).

One misbehaving tenant must not be able to starve the fleet. The bulkhead
gives every tenant:

  * an **in-flight quota** — at most N requests of THIS tenant inside the
    router at once; the (N+1)th is shed with a tenant-tagged 429
    (``TenantQuotaError``) *before* it touches the admission ladder, so it
    never consumes fleet queue capacity, and
  * a **retry budget** — a token bucket consulted before every retry hop,
    so a tenant whose requests keep failing cannot multiply its own load
    through the router's retry loop (retry storms stay inside the
    bulkhead).

The router calls ``acquire``/``release`` around each tenant-tagged request
and ``allow_retry`` before each retry hop (route/router.py). Callers are
the router's handler threads, so everything here is cross-thread and sits
under one instrumented lock. Untagged requests (``tenant=None``) bypass
bulkheads entirely — single-tenant deployments pay nothing.

Quota sheds raise ``TenantQuotaError`` (a ``RouterBusyError``, so HTTP
clients see an ordinary 429 + Retry-After — just tenant-tagged); the
router counts them as sheds and the pilot metrics attribute them to the
tenant by name.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, Optional, Tuple

from ..analysis import tsan
from ..route.admission import TenantQuotaError
from ..telemetry import graftel as telemetry
from .metrics import PilotMetrics


class TenantBulkheads:
    """Per-tenant in-flight quotas + retry budgets.

    ``per_tenant`` overrides the defaults for named tenants:
    ``{"acme": {"inflight_quota": 16, "retry_budget": 32}}``.
    """

    def __init__(
        self,
        inflight_quota: int = 8,
        retry_budget: int = 16,
        retry_refill_per_s: float = 8.0,
        per_tenant: Optional[Dict[str, Dict[str, float]]] = None,
        metrics: Optional[PilotMetrics] = None,
        jitter_seed: Optional[int] = None,
    ):
        if int(inflight_quota) < 1:
            raise ValueError(
                f"inflight_quota must be >= 1, got {inflight_quota}"
            )
        if int(retry_budget) < 0:
            raise ValueError(f"retry_budget must be >= 0, got {retry_budget}")
        if float(retry_refill_per_s) < 0:
            raise ValueError(
                f"retry_refill_per_s must be >= 0, got {retry_refill_per_s}"
            )
        self.inflight_quota = int(inflight_quota)
        self.retry_budget = int(retry_budget)
        self.retry_refill_per_s = float(retry_refill_per_s)
        self.per_tenant = {
            str(k): dict(v) for k, v in (per_tenant or {}).items()
        }
        self.metrics = metrics if metrics is not None else PilotMetrics()
        self._lock = tsan.instrument_lock(
            threading.Lock(), "TenantBulkheads._lock"
        )
        # Live in-flight count per tenant (router handler threads).
        self._inflight: Dict[str, int] = {}  # guarded-by: self._lock
        # Retry token buckets: remaining tokens + last refill stamp.
        self._retry_tokens: Dict[str, float] = {}  # guarded-by: self._lock
        self._retry_stamp: Dict[str, float] = {}  # guarded-by: self._lock
        # Cumulative sheds per tenant (report()/metrics mirror).
        self._shed: Dict[str, int] = {}  # guarded-by: self._lock
        self._rng = random.Random(jitter_seed)  # guarded-by: self._lock

    # --------------------------------------------------------------- quotas
    def quota_for(self, tenant: str) -> Tuple[int, int]:
        """(inflight_quota, retry_budget) for this tenant (overrides win)."""
        ov = self.per_tenant.get(tenant, {})
        return (
            int(ov.get("inflight_quota", self.inflight_quota)),
            int(ov.get("retry_budget", self.retry_budget)),
        )

    def acquire(
        self, tenant: str, klass: str = "fast", queue_depth: int = 0
    ) -> None:
        """Take one in-flight slot for ``tenant`` or shed with a
        tenant-tagged 429. Every successful acquire MUST be paired with
        ``release`` (the router does this via try/finally)."""
        tenant = str(tenant)
        quota, _ = self.quota_for(tenant)
        with self._lock:
            cur = self._inflight.get(tenant, 0)
            if cur >= quota:
                self._shed[tenant] = self._shed.get(tenant, 0) + 1
                # Jittered hint so one tenant's shed clients don't
                # re-synchronize (same reasoning as admission sheds).
                hint = 0.05 * (0.5 + self._rng.random())
            else:
                self._inflight[tenant] = cur + 1
                hint = None
        if hint is not None:
            self.metrics.count("tenant_shed_total")
            self.metrics.count_tenant(tenant, "shed")
            telemetry.event("pilot/tenant_shed", tenant=tenant, klass=klass)
            raise TenantQuotaError(
                f"tenant {tenant!r} in-flight quota ({quota}) exhausted "
                f"(bulkhead; the fleet itself may be healthy)",
                retry_after_s=hint,
                tenant=tenant,
                queue_depth=queue_depth,
                klass=klass,
            )

    def release(self, tenant: str) -> None:
        tenant = str(tenant)
        with self._lock:
            cur = self._inflight.get(tenant, 0)
            if cur <= 1:
                self._inflight.pop(tenant, None)
            else:
                self._inflight[tenant] = cur - 1

    # --------------------------------------------------------- retry budget
    def allow_retry(self, tenant: str, now: Optional[float] = None) -> bool:
        """Spend one retry token, or deny. Token bucket: ``retry_budget``
        capacity refilled at ``retry_refill_per_s`` — a tenant can burst
        ``retry_budget`` retries, then is held to the refill rate."""
        tenant = str(tenant)
        _, budget = self.quota_for(tenant)
        if budget <= 0:
            denied = True
        else:
            t = time.monotonic() if now is None else float(now)
            with self._lock:
                tokens = self._retry_tokens.get(tenant, float(budget))
                last = self._retry_stamp.get(tenant)
                if last is not None and t > last:
                    tokens = min(
                        float(budget),
                        tokens + (t - last) * self.retry_refill_per_s,
                    )
                self._retry_stamp[tenant] = t
                if tokens >= 1.0:
                    self._retry_tokens[tenant] = tokens - 1.0
                    denied = False
                else:
                    self._retry_tokens[tenant] = tokens
                    denied = True
        if denied:
            self.metrics.count("tenant_retry_denied_total")
            self.metrics.count_tenant(tenant, "retry_denied")
            telemetry.event("pilot/tenant_retry_denied", tenant=tenant)
        return not denied

    # -------------------------------------------------------------- reporters
    def inflight(self, tenant: str) -> int:
        with self._lock:
            return self._inflight.get(str(tenant), 0)

    def report(self) -> Dict:
        with self._lock:
            inflight = dict(sorted(self._inflight.items()))
            shed = dict(sorted(self._shed.items()))
            tokens = {
                k: round(v, 3)
                for k, v in sorted(self._retry_tokens.items())
            }
        return {
            "inflight_quota": self.inflight_quota,
            "retry_budget": self.retry_budget,
            "retry_refill_per_s": self.retry_refill_per_s,
            "inflight": inflight,
            "shed": shed,
            "retry_tokens": tokens,
        }
