// Native cell-list neighbor-list builder — the C++ replacement for the
// torch-cluster RadiusGraph / ase.neighborlist.neighbor_list native kernels the
// reference leans on (/root/reference/hydragnn/preprocess/utils.py:51-123).
// Host-side graph construction is the data-pipeline hot loop (SURVEY.md §3.6);
// it stays out of the XLA graph and feeds the padded-batch collator.
//
// Exposed via a C ABI for ctypes (no pybind11 in the image). Semantics match
// hydragnn_tpu/preprocess/graph_build.py exactly:
//  - flat: edges (j → i) with |p_i - p_j| <= radius, nearest-first per
//    receiver, capped at max_neighbours, ties broken by source index.
//  - periodic: pairs over all cell images within the cutoff (an atom sees its
//    own periodic copy); duplicate (i, j) pairs signal an inconsistent
//    radius/cell combination (error -2, mirroring the reference's assert).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 neighborlist.cc -o _neighborlist.so

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <unordered_set>
#include <vector>

namespace {

struct Nbr {
  double d2;
  int64_t j;
};

inline bool nbr_less(const Nbr& a, const Nbr& b) {
  if (a.d2 != b.d2) return a.d2 < b.d2;
  return a.j < b.j;
}

// Cell grid with edge >= radius: all neighbors of a point within `radius` lie
// in the 27-cell stencil around its (clamped) cell — including points up to
// one cell-length outside the bounding box.
struct CellGrid {
  double lo[3], hi[3];
  int64_t dims[3];
  std::vector<int64_t> head, next;

  CellGrid(const double* pos, int64_t n, double radius) {
    for (int k = 0; k < 3; ++k) lo[k] = hi[k] = pos[k];
    for (int64_t i = 1; i < n; ++i)
      for (int k = 0; k < 3; ++k) {
        lo[k] = std::min(lo[k], pos[3 * i + k]);
        hi[k] = std::max(hi[k], pos[3 * i + k]);
      }
    const int64_t dim_cap =
        std::max<int64_t>(1, (int64_t)std::ceil(std::cbrt((double)n))) + 1;
    for (int k = 0; k < 3; ++k) {
      double extent = hi[k] - lo[k];
      int64_t d = radius > 0 ? (int64_t)std::floor(extent / radius) : 1;
      dims[k] = std::max<int64_t>(1, std::min(d, dim_cap));
    }
    head.assign(dims[0] * dims[1] * dims[2], -1);
    next.assign(n, -1);
    for (int64_t i = 0; i < n; ++i) {
      int64_t c = cell_of(pos + 3 * i);
      next[i] = head[c];
      head[c] = i;
    }
  }

  int64_t coord(const double* p, int k) const {
    double extent = hi[k] - lo[k];
    int64_t c = extent > 0
                    ? (int64_t)((p[k] - lo[k]) / extent * (double)dims[k])
                    : 0;
    return std::min(std::max<int64_t>(c, 0), dims[k] - 1);
  }

  int64_t cell_of(const double* p) const {
    return (coord(p, 0) * dims[1] + coord(p, 1)) * dims[2] + coord(p, 2);
  }

  // Visit every point j with |pos_j - q| <= radius (squared test via r2).
  template <typename F>
  void for_neighbors(const double* pos, const double* q, double r2,
                     F&& fn) const {
    int64_t cx = coord(q, 0), cy = coord(q, 1), cz = coord(q, 2);
    for (int64_t dx = -1; dx <= 1; ++dx)
      for (int64_t dy = -1; dy <= 1; ++dy)
        for (int64_t dz = -1; dz <= 1; ++dz) {
          int64_t x = cx + dx, y = cy + dy, z = cz + dz;
          if (x < 0 || x >= dims[0] || y < 0 || y >= dims[1] || z < 0 ||
              z >= dims[2])
            continue;
          for (int64_t j = head[(x * dims[1] + y) * dims[2] + z]; j >= 0;
               j = next[j]) {
            const double* pj = pos + 3 * j;
            double d2 = 0;
            for (int k = 0; k < 3; ++k) {
              double diff = q[k] - pj[k];
              d2 += diff * diff;
            }
            if (d2 <= r2) fn(j, d2);
          }
        }
  }
};

}  // namespace

extern "C" {

// Returns edge count, or -1 if `cap` is too small.
int64_t hg_radius_graph_flat(const double* pos, int64_t n, double radius,
                             int64_t max_neighbours, int loop,
                             int64_t* senders, int64_t* receivers,
                             int64_t cap) {
  if (n == 0) return 0;
  const double r2 = radius * radius;
  CellGrid grid(pos, n, radius);

  int64_t count = 0;
  std::vector<Nbr> nbrs;
  for (int64_t i = 0; i < n; ++i) {
    nbrs.clear();
    grid.for_neighbors(pos, pos + 3 * i, r2, [&](int64_t j, double d2) {
      if (j == i && !loop) return;
      nbrs.push_back({d2, j});
    });
    std::sort(nbrs.begin(), nbrs.end(), nbr_less);
    int64_t keep = std::min<int64_t>((int64_t)nbrs.size(), max_neighbours);
    if (count + keep > cap) return -1;
    for (int64_t k = 0; k < keep; ++k) {
      senders[count] = nbrs[k].j;
      receivers[count] = i;
      ++count;
    }
  }
  return count;
}

// Periodic neighbor list over cell images. `cell` is row-major 3x3.
// Returns edge count; -1 if cap too small; -2 on duplicate (i, j) pairs
// (radius inconsistent with cell size — reference preprocess/utils.py:108-116).
int64_t hg_radius_graph_pbc(const double* pos, int64_t n, const double* cell,
                            double radius, int64_t max_neighbours, int loop,
                            int64_t* senders, int64_t* receivers,
                            double* lengths, int64_t cap) {
  if (n == 0) return 0;
  const double r2 = radius * radius;

  // Image search range per axis from the cell heights (volume / face area).
  double vol = cell[0] * (cell[4] * cell[8] - cell[5] * cell[7]) -
               cell[1] * (cell[3] * cell[8] - cell[5] * cell[6]) +
               cell[2] * (cell[3] * cell[7] - cell[4] * cell[6]);
  vol = std::fabs(vol);
  int64_t nimg[3];
  for (int k = 0; k < 3; ++k) {
    const double* a = cell + 3 * ((k + 1) % 3);
    const double* b = cell + 3 * ((k + 2) % 3);
    double cx = a[1] * b[2] - a[2] * b[1];
    double cy = a[2] * b[0] - a[0] * b[2];
    double cz = a[0] * b[1] - a[1] * b[0];
    double height = vol / std::sqrt(cx * cx + cy * cy + cz * cz);
    nimg[k] = (int64_t)std::ceil(radius / height);
  }

  struct Edge {
    int64_t src, dst;
    double len;
  };
  std::vector<Edge> edges;
  std::unordered_set<int64_t> seen;
  bool duplicate = false;
  CellGrid grid(pos, n, radius);

  // Pairs (i, j) with |pos_i - pos_j - offset| <= radius ⇔ atoms j within
  // `radius` of the query point pos_i - offset; the grid prunes both the
  // per-atom scan and (via the bbox test) whole off-boundary image passes.
  for (int64_t si = -nimg[0]; si <= nimg[0]; ++si)
    for (int64_t sj = -nimg[1]; sj <= nimg[1]; ++sj)
      for (int64_t sk = -nimg[2]; sk <= nimg[2]; ++sk) {
        double off[3];
        for (int k = 0; k < 3; ++k)
          off[k] = si * cell[0 + k] + sj * cell[3 + k] + sk * cell[6 + k];
        bool zero_shift = (si == 0 && sj == 0 && sk == 0);
        for (int64_t i = 0; i < n; ++i) {
          double q[3];
          bool outside = false;
          for (int k = 0; k < 3; ++k) {
            q[k] = pos[3 * i + k] - off[k];
            outside |= q[k] < grid.lo[k] - radius || q[k] > grid.hi[k] + radius;
          }
          if (outside) continue;
          grid.for_neighbors(pos, q, r2, [&](int64_t j, double d2) {
            if (zero_shift && i == j && !loop) return;
            if (!seen.insert(i * n + j).second) duplicate = true;
            edges.push_back({j, i, std::sqrt(d2)});
          });
        }
      }
  if (duplicate) return -2;

  std::vector<int64_t> keep;
  if (max_neighbours >= 0) {
    // Per-receiver nearest-first cap (stable on original edge order), output
    // in original edge order — mirrors graph_build._cap_neighbors.
    std::vector<std::vector<int64_t>> by_recv(n);
    for (int64_t e = 0; e < (int64_t)edges.size(); ++e)
      by_recv[edges[e].dst].push_back(e);
    for (int64_t r = 0; r < n; ++r) {
      auto& es = by_recv[r];
      if ((int64_t)es.size() > max_neighbours) {
        std::stable_sort(es.begin(), es.end(), [&](int64_t a, int64_t b) {
          return edges[a].len < edges[b].len;
        });
        es.resize(max_neighbours);
      }
      keep.insert(keep.end(), es.begin(), es.end());
    }
    std::sort(keep.begin(), keep.end());
  } else {
    keep.resize(edges.size());
    for (int64_t e = 0; e < (int64_t)edges.size(); ++e) keep[e] = e;
  }

  if ((int64_t)keep.size() > cap) return -1;
  int64_t count = 0;
  for (int64_t e : keep) {
    senders[count] = edges[e].src;
    receivers[count] = edges[e].dst;
    lengths[count] = edges[e].len;
    ++count;
  }
  return count;
}

}  // extern "C"
