"""Native (C++) data-pipeline kernels, loaded via ctypes.

The reference gets its neighbor-list construction from torch-cluster's CUDA/C++
RadiusGraph and ase's C neighbor list (/root/reference/hydragnn/preprocess/
utils.py:51-123). Here the equivalent is a small C++ cell-list library,
compiled on first use with the system toolchain (no pybind11 in the image —
plain C ABI + ctypes keeps the build to one g++ invocation).

``available()`` is False when compilation fails (or HYDRAGNN_NATIVE=0), and
callers in preprocess/graph_build.py fall back to the numpy/cKDTree path; both
paths produce identical edge sets (tests/test_native_neighborlist.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "neighborlist.cc")
_SO = os.path.join(_HERE, "_neighborlist.so")

_lib = None
_tried = False


def _compile() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _SO,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("HYDRAGNN_NATIVE", "1") in ("0", "false", "False"):
        return None
    stale = not os.path.exists(_SO) or (
        os.path.exists(_SRC) and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
    )
    if stale and not _compile():
        return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    i64, f64p, i64p = (
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
    )
    lib.hg_radius_graph_flat.restype = i64
    lib.hg_radius_graph_flat.argtypes = [
        f64p, i64, ctypes.c_double, i64, ctypes.c_int, i64p, i64p, i64,
    ]
    lib.hg_radius_graph_pbc.restype = i64
    lib.hg_radius_graph_pbc.argtypes = [
        f64p, i64, f64p, ctypes.c_double, i64, ctypes.c_int,
        i64p, i64p, f64p, i64,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def radius_graph(
    pos: np.ndarray, radius: float, max_neighbours: int, loop: bool = False
) -> np.ndarray:
    """Flat radius graph via the native cell list → edge_index [2, E]
    (edges j → i, nearest-first per receiver, capped at max_neighbours)."""
    lib = _load()
    assert lib is not None, "native neighborlist unavailable"
    pos = np.ascontiguousarray(pos, dtype=np.float64)
    n = pos.shape[0]
    cap = max(n * max_neighbours, 1)
    senders = np.empty(cap, dtype=np.int64)
    receivers = np.empty(cap, dtype=np.int64)
    count = lib.hg_radius_graph_flat(
        pos, n, float(radius), int(max_neighbours), int(loop),
        senders, receivers, cap,
    )
    assert count >= 0, "native neighborlist capacity error"
    return np.stack([senders[:count], receivers[:count]])


def periodic_radius_graph(
    pos: np.ndarray,
    cell: np.ndarray,
    radius: float,
    max_neighbours: Optional[int] = None,
    loop: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Periodic neighbor list over cell images → (edge_index [2, E],
    lengths [E]). Raises the reference's duplicate-edge assertion when the
    cutoff is inconsistent with the cell size."""
    lib = _load()
    assert lib is not None, "native neighborlist unavailable"
    pos = np.ascontiguousarray(pos, dtype=np.float64)
    cell = np.ascontiguousarray(np.asarray(cell, dtype=np.float64).reshape(3, 3))
    n = pos.shape[0]
    cap = max(64 * n, 64)
    while True:
        senders = np.empty(cap, dtype=np.int64)
        receivers = np.empty(cap, dtype=np.int64)
        lengths = np.empty(cap, dtype=np.float64)
        count = lib.hg_radius_graph_pbc(
            pos, n, cell, float(radius),
            -1 if max_neighbours is None else int(max_neighbours),
            int(loop), senders, receivers, lengths, cap,
        )
        if count == -1:
            cap *= 4
            continue
        assert count != -2, (
            "Adding periodic boundary conditions would result in duplicate "
            "edges. Cutoff radius must be reduced or system size increased."
        )
        return (
            np.stack([senders[:count], receivers[:count]]),
            lengths[:count],
        )
