"""Prediction entry — ``hydragnn_tpu.run_prediction(config_or_path)``
(reference /root/reference/hydragnn/run_prediction.py:27-80): data → model →
restore checkpoint → test() → optional denormalize. Returns
(error, error_rmse_task, true_values, predicted_values)."""

from __future__ import annotations

import json
import os
from functools import singledispatch

from .models.create import create_model_config, init_model_variables
from .parallel.distributed import setup_ddp
from .postprocess.postprocess import output_denormalize
from .preprocess.load_data import dataset_loading_and_splitting
from .train.train_validate_test import TrainingDriver
from .train.trainer import create_train_state
from .utils.config_utils import get_log_name_config, update_config
from .utils.model import load_existing_model
from .utils.optimizer import select_optimizer
from .utils.print_utils import print_distributed


@singledispatch
def run_prediction(config, mesh=None):
    raise TypeError("Input must be filename string or configuration dictionary.")


@run_prediction.register
def _(config_file: str, mesh=None):
    with open(config_file, "r") as f:
        config = json.load(f)
    return run_prediction(config, mesh=mesh)


@run_prediction.register
def _(config: dict, mesh=None):
    os.environ.setdefault("SERIALIZED_DATA_PATH", os.getcwd())
    world_size, _rank = setup_ddp()
    # Same static contract gate as run_training, in prediction mode: the
    # epoch-loop Training knobs are not required and only the forward path
    # is shape-checked (docs/STATIC_ANALYSIS.md).
    from .analysis.contracts import gate_config

    gate_config(config, mode="prediction")
    from .parallel.distributed import config_graph_axis

    graph_axis = config_graph_axis(config)
    if mesh is None and (world_size > 1 or graph_axis > 1):
        # Same auto rule as run_training: multi-process launches evaluate
        # through the global data mesh; Training.graph_axis > 1 additionally
        # shards each graph's edges (config-level large-graph support).
        from .parallel.distributed import make_mesh

        mesh = make_mesh(graph_axis=graph_axis)

    train_loader, val_loader, test_loader, _ = dataset_loading_and_splitting(
        config=config
    )
    config = update_config(config, train_loader, val_loader, test_loader)

    model = create_model_config(
        config=config["NeuralNetwork"]["Architecture"],
        verbosity=config["Verbosity"]["level"],
    )
    example = next(iter(test_loader))
    variables = init_model_variables(model, example)
    if mesh is not None and mesh.shape.get("graph", 1) > 1:
        model = model.clone(graph_axis="graph")

    log_name = get_log_name_config(config)
    # Verified load (docs/CHECKPOINTING.md): digest-checked v2 read with the
    # corruption fallback chain — a bit-flipped latest checkpoint serves
    # predictions from the newest intact retained entry instead of dying.
    variables, _, ckpt_meta = load_existing_model(
        variables, log_name, return_meta=True
    )
    print_distributed(
        config["Verbosity"]["level"],
        f"Restored checkpoint for {log_name} "
        f"(epoch {ckpt_meta.get('epoch', '?')})",
    )

    optimizer = select_optimizer("AdamW", 1e-3)  # unused for inference
    state = create_train_state(model, variables, optimizer)
    driver = TrainingDriver(
        model, optimizer, state, mesh=mesh, verbosity=config["Verbosity"]["level"]
    )
    error, error_rmse_task, true_values, predicted_values = driver.evaluate(
        test_loader, return_values=True
    )

    if config["NeuralNetwork"]["Variables_of_interest"]["denormalize_output"]:
        true_values, predicted_values = output_denormalize(
            config["NeuralNetwork"]["Variables_of_interest"]["y_minmax"],
            true_values,
            predicted_values,
        )
    return error, error_rmse_task, true_values, predicted_values
